"""Micro-batcher: coalesce compatible in-flight resident scans into ONE
device dispatch.

Every resident-scan query pays the same ~65 ms link round trip for its
count-vector D2H regardless of payload (exec/hbm_cache design note), so
N concurrent point lookups serialized through the single-query path cost
N round trips. Queries are COMPATIBLE when they hit the same resident
table (same index log version — the table key carries file identities)
with predicates that narrow to the same resident column set; a batch of
compatible queries stacks its predicates into one jitted mask+count
launch (``hbm_cache.block_counts_batch`` / the mesh twin) and ships home
one (N, n_blocks) count matrix — the inference-serving
continuous-batching shape applied to index scans. The host leg stays
per-query and exact: each query reads only ITS candidate blocks and
re-evaluates ITS predicate there, so batched results are bit-identical
to serial execution.

Classification happens against the OPTIMIZED plan (the server's plan
cache makes that cheap): the `[Project] → Filter → IndexScan` shape
qualifies, and so does the filter-shape HYBRID union
(`[Project] → Filter → Union(index side, appended side)`) when both the
base table and its appended delta are resident (exec.hbm_cache
DeltaRegion) — those coalesce like plain scans, with the stacked hybrid
dispatch covering base+delta+deletion-bitmask for the whole batch.
Joins, aggregates, mesh-session hybrids (served per-query by the
executor's own fused mesh path), resident-ineligible predicates and
queries the selectivity zone gate routes host all take the normal
executor path (a broad predicate batched onto the device would pay the
dispatch AND read nearly every block anyway).
"""

from __future__ import annotations

from dataclasses import dataclass
from pathlib import Path
from typing import List, Optional, Tuple

from ..plan.expr import Expr
from ..plan.ir import Aggregate, Filter, IndexScan, LogicalPlan, Project, Union
from ..storage.columnar import ColumnarBatch
from ..telemetry.metrics import metrics


@dataclass
class ResidentScanRequest:
    """One classified, batchable query: everything the batched executor
    needs, plus the compatibility key it coalesces under."""

    table: object  # ResidentTable | MeshResidentTable | JoinRegion
    entry: object  # IndexLogEntry (schema for empty results)
    files: List[Path]  # the QUERY's pruned file list (subset of table's)
    predicate: Expr
    output_columns: List[str]
    batch_key: Tuple
    mesh: object = None  # non-None routes the mesh cache protocol
    # prepare_resident_predicate result from classification — carried so
    # the dispatch leg doesn't rerun the narrow pipeline per query
    prepared: object = None
    # hybrid (delta-resident) requests only: the base table's delta
    # region, and the base host leg's exact predicate (user predicate
    # conjoined with the lineage NOT-IN when files were deleted)
    delta: object = None
    host_predicate: Optional[Expr] = None
    # resident JOIN requests only: "join_agg" coalesces aggregate-joins
    # under the join-extended key (region identity + aggregation spec) —
    # one fused dispatch serves the whole batch; ``table`` holds the
    # JoinRegion so the server's latch-drop path works unchanged
    kind: str = "scan"
    group_by: Tuple = ()
    aggs: Tuple = ()


def classify(session, plan: LogicalPlan) -> Optional[ResidentScanRequest]:
    """A ResidentScanRequest when ``plan`` can ride a batched resident
    dispatch, else None (the caller executes it normally). Never raises:
    any refusal is a routing decision, not an error."""
    from ..exec.hbm_cache import (
        _max_block_frac,
        hbm_cache,
        prepare_resident_predicate,
        zone_block_fraction,
    )
    from ..exec.scan import prune_index_files

    # every batch key folds the plan's COARSE pipeline fingerprint
    # (compile.fingerprint.batch_fingerprint): shape class + index-leaf
    # versions + predicate/projection column sets — the whole-plan twin
    # of the table-identity component, so two structurally-incompatible
    # pipelines can never share a stacked dispatch even if they resolve
    # to the same resident table. Full predicate structure stays
    # per-slot in the batched executable (mixed point/range bursts keep
    # coalescing). Computed only AFTER the structural early-returns —
    # the common non-batchable plan must not pay the fingerprint walk.
    from ..compile.fingerprint import batch_fingerprint

    output_columns = list(plan.output_columns())
    node = plan
    while isinstance(node, Project):
        node = node.child
    if isinstance(node, Aggregate):
        return _classify_join_aggregate(
            session, node, output_columns, batch_fingerprint(plan)
        )
    if not isinstance(node, Filter):
        return None
    if isinstance(node.child, Union):
        return _classify_hybrid(
            session,
            node.condition,
            node.child,
            output_columns,
            batch_fingerprint(plan),
        )
    if not isinstance(node.child, IndexScan):
        return None
    fp = batch_fingerprint(plan)
    predicate = node.condition
    scan = node.child
    entry = scan.entry
    # batched results come back as the scan's required columns projected
    # to the plan's output — a Project that REORDERS within required
    # columns is fine, anything else was already excluded by plan shape
    files = prune_index_files(
        [Path(p) for p in entry.content.files()],
        predicate,
        entry.indexed_columns,
        entry.schema,
        entry.num_buckets,
    )
    if not files:
        return None  # empty scans are cheap on the normal path
    pred_cols = sorted(predicate.columns())
    mesh = session.mesh if session.mesh is not None else None
    if mesh is not None and mesh.devices.size > 1:
        from ..exec.mesh_cache import mesh_cache

        table = mesh_cache.resident_for(files, pred_cols, mesh)
        if table is None:
            return None
        prepared = prepare_resident_predicate(table.columns, predicate)
        if prepared is None:
            return None
        # mesh streaming tables batch only within a WINDOW GENERATION —
        # the single-chip rule below, now that the mesh ladder accepts
        # the compressed-streaming rung
        gen = getattr(table, "window_gen", None)
        batch_key = (fp, id(table), frozenset(prepared[1])) + (
            (gen,) if gen is not None else ()
        )
        return ResidentScanRequest(
            table,
            entry,
            files,
            predicate,
            output_columns,
            batch_key,
            mesh,
            prepared,
        )
    table = hbm_cache.resident_for(files, pred_cols)
    if table is None:
        return None
    # same pre-dispatch selectivity gate as the single-query scan: a
    # predicate that cannot prune blocks reads nearly everything host-side
    # regardless, so batching its dispatch wins nothing
    frac = zone_block_fraction(table, predicate)
    if frac is not None and _max_block_frac() < 1.0 and frac >= _max_block_frac():
        return None
    prepared = prepare_resident_predicate(table.columns, predicate)
    if prepared is None:
        return None
    # streaming-tier tables batch only within a WINDOW GENERATION: the
    # generation bumps when a device failure tears the slab pair down
    # (residency.streaming), and a batch must never span that
    # discontinuity — half its queries would have classified against
    # state the other half's windows no longer reflect
    gen = getattr(table, "window_gen", None)
    batch_key = (fp, id(table), frozenset(prepared[1])) + (
        (gen,) if gen is not None else ()
    )
    return ResidentScanRequest(
        table,
        entry,
        files,
        predicate,
        output_columns,
        batch_key,
        None,
        prepared,
    )


def _classify_hybrid(
    session,
    predicate: Expr,
    union: LogicalPlan,
    output_columns: List[str],
    fp: Tuple,
) -> Optional[ResidentScanRequest]:
    """Classify a filter-shape hybrid union for the batched hybrid
    dispatch: base table AND delta region must be resident and the
    predicate must ride the shared encodings. Eligibility (residency,
    pruning, zone gate, host predicate) is exec.delta's
    resolve_hybrid_residency — the SAME procedure the executor's fused
    path runs, so a query never routes differently served vs collected.
    Mesh sessions decline — their hybrid queries are served per-query by
    the executor's fused mesh path (one shard_map dispatch each), which
    the normal path already provides."""
    from ..exec.delta import (
        prepare_hybrid_predicate,
        resolve_hybrid_residency,
    )
    from ..plan.rules.hybrid_scan import parse_hybrid_union

    if session.mesh is not None and session.mesh.devices.size > 1:
        return None
    info = parse_hybrid_union(union)
    if info is None:
        return None
    res = resolve_hybrid_residency(info, predicate)
    if res.status != "ok":
        return None
    prepared = prepare_hybrid_predicate(
        res.table.columns, res.delta.oov, predicate
    )
    if prepared is None:
        return None
    if any(
        n.split("\x00", 1)[0] not in res.delta.columns for n in prepared[1]
    ):
        return None
    return ResidentScanRequest(
        res.table,
        info.entry,
        res.files,
        predicate,
        output_columns,
        (fp, id(res.table), id(res.delta), frozenset(prepared[1])),
        None,
        prepared,
        res.delta,
        res.host_predicate,
    )


def _classify_join_aggregate(
    session, agg: Aggregate, output_columns: List[str], fp: Tuple
) -> Optional[ResidentScanRequest]:
    """Classify an Aggregate([Project](Join)) plan for the batched
    resident aggregate-join: both sides must resolve to pristine
    bucketed index scans with a registered join region covering the
    group/agg columns, and the spec must ride the device (the SAME
    resolve_join_residency + region_agg_plan pair the executor's fused
    arm runs — a query never routes differently served vs collected).
    Identical-spec queries coalesce under (region identity, spec): the
    whole batch is served from ONE fused dispatch. Mesh sessions
    decline — the executor's sharded fused arm serves them per-query."""
    from ..exec.join_residency import (
        orient_join_aggregate,
        region_agg_plan,
        resolve_join_residency,
    )

    if session.mesh is not None and session.mesh.devices.size > 1:
        return None
    oriented = orient_join_aggregate(agg)
    if oriented is None:
        return None
    left_plan, right_plan, lk, rk, group_by, aggs = oriented
    need = list(
        dict.fromkeys(group_by + [a.column for a in aggs if a.column])
    )
    res = resolve_join_residency(
        left_plan, right_plan, lk, rk, payload_columns=need
    )
    if res.status != "ok":
        return None
    if region_agg_plan(res.region, group_by, aggs) is None:
        return None
    spec = (tuple(group_by), tuple((a.fn, a.column, a.name) for a in aggs))
    return ResidentScanRequest(
        res.region,
        None,
        [],
        None,
        output_columns,
        (fp, id(res.region), "join_agg", spec),
        None,
        None,
        None,
        None,
        "join_agg",
        tuple(group_by),
        tuple(aggs),
    )


def execute_batch(
    requests: List[ResidentScanRequest],
) -> Optional[List[ColumnarBatch]]:
    """Results for a compatible batch — ONE device dispatch, then each
    query's exact host leg over its own candidate blocks. None when the
    stacked dispatch declines (caller falls back to per-query execution);
    device errors propagate so the server can latch degradation."""
    from ..exec.hbm_cache import hbm_cache
    from ..exec.scan import _resident_parts

    if requests[0].kind == "join_agg":
        # the whole batch shares one (region, spec) key, so ONE fused
        # aggregate-join dispatch serves every query in it
        group = hbm_cache.join_agg(
            requests[0].table,
            list(requests[0].group_by),
            list(requests[0].aggs),
        )
        if group is None:
            return None  # spec declined since classification: per-query
        results = [group.select(list(r.output_columns)) for r in requests]
        metrics.incr("serve.batch.coalesced", len(requests))
        metrics.incr("scan.path.resident_join_agg", len(requests))
        return results

    table = requests[0].table
    predicates = [r.predicate for r in requests]
    prepared = [r.prepared for r in requests]
    if requests[0].delta is not None:
        # hybrid batch: ONE stacked base+delta dispatch, then each
        # query's exact host legs (base blocks from mmap with the
        # lineage NOT-IN re-applied, delta blocks from the host-held
        # appended batch)
        delta = requests[0].delta
        pairs = hbm_cache.hybrid_block_counts_batch(
            table, delta, predicates, prepared
        )
        if pairs is None:
            return None
        results = []
        for r, (base_c, delta_c) in zip(requests, pairs):
            parts = _resident_parts(
                table,
                r.files,
                r.output_columns,
                r.host_predicate,
                base_c,
                path_metric=None,
            )
            parts += hbm_cache.delta_parts(
                delta, r.predicate, r.output_columns, delta_c
            )
            metrics.incr("scan.path.resident_hybrid")
            results.append(_concat_or_empty(parts, r))
        metrics.incr("serve.batch.coalesced", len(requests))
        return results
    if requests[0].mesh is not None:
        from ..exec.mesh_cache import mesh_cache

        counts = mesh_cache.block_counts_batch(table, predicates, prepared)
        if counts is None:
            return None
        results = []
        for r, c in zip(requests, counts):
            parts = mesh_cache.collect_parts(
                table, r.files, r.output_columns, r.predicate, c
            )
            results.append(_concat_or_empty(parts, r))
        metrics.incr("serve.batch.coalesced", len(requests))
        return results
    counts = hbm_cache.block_counts_batch(table, predicates, prepared)
    if counts is None:
        return None
    results = []
    for r, c in zip(requests, counts):
        parts = _resident_parts(
            table, r.files, r.output_columns, r.predicate, c
        )
        results.append(_concat_or_empty(parts, r))
    metrics.incr("serve.batch.coalesced", len(requests))
    return results


def _concat_or_empty(parts, r: ResidentScanRequest) -> ColumnarBatch:
    from ..exec.scan import empty_batch_for

    if parts:
        return ColumnarBatch.concat(parts)
    empty = empty_batch_for(r.output_columns, r.entry.schema)
    if empty is not None:
        return empty
    # no logged schema (cannot happen for covering indexes, which always
    # log one): fall back to a 0-row read of the first file
    import numpy as np

    from ..storage import layout

    eb = layout.read_batch(r.files[0], columns=r.output_columns)
    return eb.take(np.array([], dtype=np.int64))
