"""Multi-tenant serving state: per-tenant quotas, weighted-fair
scheduling, circuit breakers, and drain-rate-derived retry-after.

One QueryServer fronts many tenants (the Presto-on-GPUs setting in
PAPERS.md: thousands of dashboards sharing one accelerator-backed
engine). A single FIFO lets any one tenant's burst occupy the whole
admission budget and every worker — isolation, not peak throughput,
decides whether the system survives that burst. This module holds the
per-tenant state the server schedules over:

* **TenantPolicy / TenantState** — quotas (queue-depth and in-flight
  caps) and weight from the ``hyperspace.serve.tenant.*`` conf family,
  plus the tenant's queue, counters, and latency reservoir;
* **weighted-fair dispatch** (``pick_tenant_locked``) — smooth weighted
  round-robin over the tenants that have queued work and in-flight
  headroom: each pick raises every eligible tenant's deficit by its
  weight and charges the chosen tenant the eligible total, so over any
  contention window each tenant's share of dispatches converges to
  weight/sum(weights) without starving anyone (the classic nginx
  balancing recurrence, applied to query dispatch);
* **CircuitBreaker** — per-tenant, opened by consecutive deadline
  misses: a tenant whose deadlines keep lapsing is *adding* queue wait
  for everyone while getting nothing itself, so its submissions are
  rejected for a cooldown, then HALF-OPEN admits exactly one probe —
  a clean finish closes the circuit, another miss re-opens it;
* **drain rate** — completions-per-second over a sliding window, so
  ``AdmissionRejected.retry_after_s`` reflects the tenant's *observed*
  throughput (queue depth / drain rate) instead of a constant guess.

Thread-safety: every mutating method here is called with the server's
``_cond`` lock held (the ``_locked`` suffix convention); the module has
no locks of its own — one lock orders admission, dispatch, and breaker
transitions, which is what makes the fairness recurrence exact.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..telemetry.metrics import metrics

DEFAULT_TENANT = "default"

# breaker states (stats() strings)
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


def _snapshot_recorder(reason: str) -> None:
    """Freeze the flight recorder around a breaker transition — the
    post-mortem wants the queries that led to the open (rate-limited
    per reason; an O(ring) copy, safe under the server lock)."""
    from ..telemetry.recorder import flight_recorder

    flight_recorder.snapshot(reason)


def latency_percentiles_ms(latencies) -> dict:
    """``{"latency_p50_ms", "latency_p99_ms"}`` from a latency-seconds
    reservoir (empty dict when empty) — the ONE percentile formula both
    the per-tenant and the global stats() report."""
    lat = sorted(latencies)
    if not lat:
        return {}
    return {
        "latency_p50_ms": round(1e3 * lat[len(lat) // 2], 3),
        "latency_p99_ms": round(
            1e3 * lat[min(len(lat) - 1, int(len(lat) * 0.99))], 3
        ),
    }


@dataclass(frozen=True)
class TenantPolicy:
    """Quotas + weight for one tenant (conf.serve_tenant_policy)."""

    weight: float = 1.0
    max_queue: int = 32
    max_inflight: int = 0  # <= 0: no per-tenant in-flight cap

    def inflight_cap(self) -> Optional[int]:
        return self.max_inflight if self.max_inflight > 0 else None


class CircuitBreaker:
    """Per-tenant deadline-miss breaker. All transitions run under the
    server lock; ``time`` flows in as an argument so tests drive the
    clock deterministically."""

    def __init__(self, miss_threshold: int, open_s: float):
        self.miss_threshold = max(int(miss_threshold), 1)
        self.open_s = float(open_s)
        self.state = CLOSED
        self.consecutive_misses = 0
        self.open_until = 0.0
        self.probe_inflight = False
        self.opens = 0
        self.probes = 0
        self.closes = 0

    def admit_locked(self, now: float) -> "tuple[bool, Optional[float]]":
        """(admitted, retry_after_s). HALF-OPEN admits exactly one probe
        at a time; OPEN transitions to HALF-OPEN once the cooldown
        lapses (the next submission IS the probe)."""
        if self.state == CLOSED:
            return True, None
        if self.state == OPEN:
            if now < self.open_until:
                return False, max(self.open_until - now, 0.001)
            self.state = HALF_OPEN
            self.probe_inflight = True
            return True, None
        # HALF_OPEN: one probe in flight decides the verdict; everyone
        # else waits for it rather than stampeding a maybe-sick tenant
        if self.probe_inflight:
            return False, max(self.open_s / 4, 0.001)
        self.probe_inflight = True
        return True, None

    def note_probe_admitted_locked(self) -> None:
        """Count the probe once it SURVIVES every admission gate — a
        probe slot granted here but rejected by a later quota gate never
        ran, and counting it would grow probes unboundedly under
        sustained overload."""
        self.probes += 1
        metrics.incr("serve.breaker.probe")

    def record_miss_locked(self, now: float, probe: bool = False) -> None:
        """A deadline miss. CLOSED opens after ``miss_threshold``
        consecutive misses. In HALF-OPEN only the PROBE's miss re-opens:
        leftover pre-open queries draining their doomed deadlines must
        neither free the probe slot nor flap the state under the probe
        that is deciding (their misses still count toward the streak)."""
        self.consecutive_misses += 1
        if self.state == HALF_OPEN:
            if probe:
                self.state = OPEN
                self.open_until = now + self.open_s
                self.probe_inflight = False
                self.opens += 1
                metrics.incr("serve.breaker.opened")
                _snapshot_recorder("breaker_open")
            return
        if (
            self.state == CLOSED
            and self.consecutive_misses >= self.miss_threshold
        ):
            self.state = OPEN
            self.open_until = now + self.open_s
            self.probe_inflight = False
            self.opens += 1
            metrics.incr("serve.breaker.opened")
            _snapshot_recorder("breaker_open")

    def record_success_locked(self) -> None:
        self.consecutive_misses = 0
        self.probe_inflight = False
        if self.state != CLOSED:
            self.state = CLOSED
            self.closes += 1
            metrics.incr("serve.breaker.closed")

    def snapshot_locked(self) -> dict:
        return {
            "state": self.state,
            "consecutive_misses": self.consecutive_misses,
            "opens": self.opens,
            "probes": self.probes,
            "closes": self.closes,
        }


class TenantState:
    """One tenant's queue, quotas, counters, and breaker. Mutated only
    under the server lock."""

    def __init__(
        self,
        name: str,
        policy: TenantPolicy,
        breaker: CircuitBreaker,
        drain_window_s: float,
    ):
        self.name = name
        self.policy = policy
        self.breaker = breaker
        self.drain_window_s = float(drain_window_s)
        self.queue: "deque" = deque()  # _Request entries, FIFO per tenant
        self.inflight = 0
        self.deficit = 0.0  # smooth-WRR credit
        # counters (mirrored into stats()["tenants"][name])
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.shed = 0
        self.rejected_breaker = 0
        self.deadline_missed = 0
        self.cancelled = 0
        self.batched_queries = 0
        self.latencies: "deque[float]" = deque(maxlen=2048)
        # completion timestamps (monotonic) for the drain-rate window
        self.completions: "deque[float]" = deque(maxlen=1024)

    # -- drain rate ----------------------------------------------------------
    def drain_rate_locked(self, now: Optional[float] = None) -> Optional[float]:
        """Completions per second over the sliding window; None until the
        tenant has at least one windowed completion (callers fall back
        to the service-time estimate)."""
        now = time.monotonic() if now is None else now
        cutoff = now - self.drain_window_s
        while self.completions and self.completions[0] < cutoff:
            self.completions.popleft()
        if not self.completions:
            return None
        # rate over the window actually covered, not the full window: a
        # tenant that completed 5 queries in the last 0.2s drains at
        # 25/s, and telling its clients to wait depth/0.5 would be a lie
        span = max(now - self.completions[0], 1e-3)
        return len(self.completions) / span

    def retry_after_locked(
        self, fallback_s: float, now: Optional[float] = None
    ) -> float:
        """Seconds until this tenant's backlog plausibly has room:
        (depth+1)/drain-rate, clamped; the EWMA-derived fallback serves
        tenants with no completions in the window yet."""
        rate = self.drain_rate_locked(now)
        if rate is None or rate <= 0:
            return max(fallback_s, 0.001)
        return min(max((len(self.queue) + 1) / rate, 0.001), 300.0)

    def note_completion_locked(self, now: float, latency_s: Optional[float]) -> None:
        self.completed += 1
        self.completions.append(now)
        if latency_s is not None:
            self.latencies.append(latency_s)

    def snapshot_locked(self) -> dict:
        """Counters only — O(1), safe under the server lock. The caller
        adds percentiles from a latency copy AFTER releasing the lock
        (sorting reservoirs under _cond would stall dispatch)."""
        return {
            "weight": self.policy.weight,
            "max_queue": self.policy.max_queue,
            "max_inflight": self.policy.max_inflight,
            "queue_depth": len(self.queue),
            "inflight": self.inflight,
            "submitted": self.submitted,
            "completed": self.completed,
            "failed": self.failed,
            "shed": self.shed,
            "rejected_breaker": self.rejected_breaker,
            "deadline_missed": self.deadline_missed,
            "cancelled": self.cancelled,
            "batched_queries": self.batched_queries,
            "breaker": self.breaker.snapshot_locked(),
        }


def pick_tenant_locked(
    tenants: Dict[str, TenantState],
) -> Optional[TenantState]:
    """The next tenant to dispatch from — smooth weighted round-robin
    over tenants with queued work and in-flight headroom. Returns None
    when no tenant is eligible (empty queues, or every backlogged
    tenant is at its in-flight cap — the caller waits on the cond).

    The recurrence: every eligible tenant gains ``weight`` credit, the
    highest-credit tenant is picked and pays the eligible total. Over N
    picks with stable eligibility each tenant is picked ~N*w/W times
    with bounded burstiness (never more than one extra turn ahead of
    its entitlement) — the fairness bound bench config 15 scores."""
    eligible: List[TenantState] = []
    for t in tenants.values():
        if not t.queue:
            continue
        cap = t.policy.inflight_cap()
        if cap is not None and t.inflight >= cap:
            continue
        eligible.append(t)
    if not eligible:
        return None
    total = 0.0
    best: Optional[TenantState] = None
    for t in eligible:
        total += t.policy.weight
        t.deficit += t.policy.weight
        if best is None or t.deficit > best.deficit:
            best = t
    best.deficit -= total
    return best
