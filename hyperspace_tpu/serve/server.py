"""QueryServer: concurrent query serving over one session.

The session API executes one query per ``collect()`` call on the calling
thread; the north star serves heavy concurrent traffic. This server puts
a BOUNDED admission queue and a worker pool between callers and the
executor:

* **admission control** — a full queue rejects immediately with the
  current depth and a retry-after estimate instead of queueing unbounded
  latency (load shedding at the front door, not timeout storms at the
  back);
* **per-query deadlines** — a query whose deadline passes while queued
  is failed without executing (its slot goes to a query that can still
  make it); execution itself is not preempted, so the deadline bounds
  QUEUE time exactly and service time statistically (see stats);
* **micro-batching** — a worker that dequeues a batchable resident scan
  drains every compatible queued request and serves them with ONE device
  dispatch (serve.batcher); incompatible traffic flows around the batch
  through the other workers;
* **plan caching** — optimized plans are cached across queries keyed by
  normalized plan signature, invalidated by index-log version
  (serve.plan_cache);
* **graceful degradation** — a device failure mid-serve (or a
  deviceprobe first-touch verdict of "wedged") latches the server onto
  the host engine: the failed batch re-executes host-side with identical
  results, the resident table is dropped, and every later query routes
  host until the process is restarted. Latched beats flapping: the
  wedged-tunnel failure mode hangs, so each retry would cost a timeout.

Tickets: ``submit()`` returns a QueryTicket immediately; ``result()``
blocks for that query only. Worker threads execute each query under a
scoped metrics child (telemetry.metrics), so every ticket carries
attributable counters/timers — its own for single execution, its
batch's shared scope for coalesced execution (a per-query split of one
stacked launch would be fiction).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..exceptions import HyperspaceException
from ..storage.columnar import ColumnarBatch
from ..telemetry.metrics import metrics, reliability_snapshot
from . import batcher
from .plan_cache import PlanCache


class AdmissionRejected(HyperspaceException):
    """Queue full: retry after ``retry_after_s`` (an estimate from the
    current depth and recent service times) or shed the request."""

    def __init__(self, queue_depth: int, retry_after_s: float):
        super().__init__(
            f"admission rejected: queue full at depth {queue_depth}; "
            f"retry after ~{retry_after_s:.3f}s"
        )
        self.queue_depth = queue_depth
        self.retry_after_s = retry_after_s


class DeadlineExceeded(HyperspaceException):
    pass


class ServerClosed(HyperspaceException):
    pass


@dataclass
class ServeConfig:
    max_workers: int = 4
    max_queue: int = 64
    # applied when submit() passes no deadline; None = no deadline
    default_deadline_s: Optional[float] = None
    # largest number of compatible queries one dispatch coalesces
    batch_max: int = 64
    plan_cache_entries: int = 256
    # tests construct paused servers (submit a burst, then start()) to
    # make coalescing deterministic; production keeps the default
    autostart: bool = True
    # how often the submit path consults crash recovery: at most one
    # background sweep per interval rolls back abandoned writers
    # (transient log head + expired lease) so a serving process heals
    # indexes a dead builder left wedged. <= 0 disables.
    recovery_sweep_interval_s: float = 60.0


class QueryTicket:
    """Handle for one submitted query. ``result()`` blocks until the
    server finishes it (or ``timeout`` passes — TimeoutError), then
    returns the ColumnarBatch or raises what execution raised."""

    def __init__(self, deadline_at: Optional[float]):
        self._done = threading.Event()
        self._result: Optional[ColumnarBatch] = None
        self._error: Optional[BaseException] = None
        self.submitted_at = time.monotonic()
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.deadline_at = deadline_at
        self.batch_size = 1  # queries sharing this one's device dispatch
        self.metrics: Optional[dict] = None  # per-query scoped snapshot

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> ColumnarBatch:
        if not self._done.wait(timeout):
            raise TimeoutError("query still in flight")
        if self._error is not None:
            raise self._error
        return self._result

    @property
    def wait_s(self) -> Optional[float]:
        if self.started_at is None:
            return None
        return self.started_at - self.submitted_at

    @property
    def latency_s(self) -> Optional[float]:
        if self.finished_at is None:
            return None
        return self.finished_at - self.submitted_at


class _Request:
    __slots__ = ("df", "plan", "resident", "ticket")

    def __init__(self, df, plan, resident, ticket):
        self.df = df
        self.plan = plan
        self.resident = resident  # Optional[batcher.ResidentScanRequest]
        self.ticket = ticket


class QueryServer:
    def __init__(self, session, config: Optional[ServeConfig] = None):
        self.session = session
        self.config = config or ServeConfig()
        self.plan_cache = PlanCache(self.config.plan_cache_entries)
        self._cond = threading.Condition()
        self._queue: "deque[_Request]" = deque()
        self._workers: List[threading.Thread] = []
        self._closed = False
        # host latch-down is an Event, not a lock-guarded bool: workers
        # consult it on every query's hot path, and an Event read is
        # race-free without taking _cond (the HS010 finding: the bool
        # was written under _cond but read lock-free in three places)
        self._host_latch = threading.Event()
        self._degraded_reason: Optional[str] = None
        # serving stats (guarded by _cond's lock)
        self._submitted = 0
        self._completed = 0
        self._failed = 0
        self._shed = 0
        self._deadline_missed = 0
        self._dispatches = 0  # device dispatches for batched queries
        self._batched_queries = 0
        self._batch_sizes: Dict[int, int] = {}
        self._latencies: "deque[float]" = deque(maxlen=4096)
        self._waits: "deque[float]" = deque(maxlen=4096)
        self._ewma_service_s = 0.01
        self._recovery_sweeps = 0
        self._recovered_indexes = 0
        self._next_recovery_sweep = 0.0  # monotonic; 0 = sweep on first submit
        if self.config.autostart:
            self.start()

    # -- lifecycle -----------------------------------------------------------
    @property
    def closed(self) -> bool:
        return self._closed

    def start(self) -> "QueryServer":
        """Spawn the worker pool (idempotent)."""
        with self._cond:
            if self._closed:
                raise ServerClosed("query server is closed.")
            missing = self.config.max_workers - len(self._workers)
            for i in range(missing):
                t = threading.Thread(
                    target=self._worker_loop,
                    daemon=True,
                    name=f"hyperspace-serve-{len(self._workers)}",
                )
                self._workers.append(t)
                t.start()
        return self

    def close(self, timeout_s: float = 10.0) -> None:
        """Stop accepting work, fail queued queries with ServerClosed,
        and join the workers (in-flight queries finish)."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            pending = list(self._queue)
            self._queue.clear()
            self._cond.notify_all()
            workers = list(self._workers)
        for req in pending:
            self._finish(req.ticket, error=ServerClosed("server closed."))
        for t in workers:
            t.join(timeout_s)

    # -- admission -----------------------------------------------------------
    def submit(self, df, deadline_s: Optional[float] = None) -> QueryTicket:
        """Enqueue a DataFrame for execution. Raises AdmissionRejected
        when the queue is full (backpressure — the caller decides whether
        to retry, degrade, or shed), ServerClosed after close()."""
        if df.session is not self.session:
            raise HyperspaceException(
                "Cannot serve a DataFrame from a different session."
            )
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        deadline_at = (
            time.monotonic() + deadline_s if deadline_s is not None else None
        )
        # recovery consulted on submit: a throttled background sweep heals
        # indexes whose writer died (the serving process is often the only
        # long-lived process around to notice)
        self._maybe_recovery_sweep()
        # plan + batchability resolved at submit time: the plan cache
        # makes repeats ~two dict probes, and classified requests let the
        # worker's coalescing scan stay a pure queue walk under the lock
        ticket = QueryTicket(deadline_at)
        try:
            plan = self.plan_cache.optimized_plan(df)
            resident = (
                None
                if self._consult_device_latch()
                else batcher.classify(self.session, plan)
            )
        except Exception as e:  # noqa: BLE001 - planning failure = query failure
            # planning failures (unknown columns, vanished files) belong
            # to the QUERY, not the server: the ticket carries them and
            # admission still succeeds (and counts as a submission, so
            # stats() can never report failed > submitted)
            metrics.incr("serve.plan_error")
            metrics.incr("serve.submitted")
            with self._cond:
                self._submitted += 1
            self._finish(ticket, error=e)
            return ticket
        req = _Request(df, plan, resident, ticket)
        with self._cond:
            if self._closed:
                raise ServerClosed("query server is closed.")
            if len(self._queue) >= self.config.max_queue:
                self._shed += 1
                metrics.incr("serve.shed")
                raise AdmissionRejected(
                    len(self._queue), self._retry_after_locked()
                )
            self._submitted += 1
            self._queue.append(req)
            self._cond.notify()
        metrics.incr("serve.submitted")
        return ticket

    def _maybe_recovery_sweep(self) -> None:
        interval = self.config.recovery_sweep_interval_s
        if interval is None or interval <= 0:
            return
        now = time.monotonic()
        with self._cond:
            if now < self._next_recovery_sweep:
                return
            self._next_recovery_sweep = now + interval
        threading.Thread(
            target=self._recovery_sweep, daemon=True, name="hyperspace-serve-recovery"
        ).start()

    def _recovery_sweep(self) -> None:
        from ..reliability.recovery import recover_abandoned_indexes

        try:
            n = recover_abandoned_indexes(
                self.session.conf.system_path(), self.session.conf
            )
        except Exception:  # noqa: BLE001
            # counted, not raised: a failed sweep must never take down
            # serving — the next interval retries
            metrics.incr("serve.recovery_sweep_error")
            return
        metrics.incr("serve.recovery_sweep")
        with self._cond:
            self._recovery_sweeps += 1
            self._recovered_indexes += n
        if n:
            # recovered indexes changed the log: cached plans may bind to
            # rolled-back versions, and the TTL catalog cache may hold
            # the transient view
            self.session.collection_manager.clear_cache()

    def _retry_after_locked(self) -> float:
        backlog = len(self._queue) / max(self.config.max_workers, 1)
        return max(backlog * self._ewma_service_s, 0.001)

    # -- worker --------------------------------------------------------------
    def _worker_loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if not self._queue:  # closed and drained
                    return
                req = self._queue.popleft()
                batch = [req]
                if req.resident is not None and not self._host_latch.is_set():
                    batch += self._drain_compatible_locked(req)
            now = time.monotonic()
            live: List[_Request] = []
            for r in batch:
                if r.ticket.deadline_at is not None and now > r.ticket.deadline_at:
                    self._miss_deadline(r)
                else:
                    live.append(r)
            if not live:
                continue
            if len(live) == 1 or live[0].resident is None:
                for r in live:
                    self._execute_single(r)
            else:
                self._execute_batch(live)

    def _drain_compatible_locked(self, head: _Request) -> List[_Request]:
        """Pull every queued request sharing ``head``'s batch key (same
        resident table identity + resident column set), preserving the
        queue order of everything else. Called with the lock held."""
        key = head.resident.batch_key
        taken: List[_Request] = []
        keep: "deque[_Request]" = deque()
        while self._queue and len(taken) + 1 < self.config.batch_max:
            r = self._queue.popleft()
            if r.resident is not None and r.resident.batch_key == key:
                taken.append(r)
            else:
                keep.append(r)
        keep.extend(self._queue)
        self._queue.clear()
        self._queue.extend(keep)
        return taken

    # -- execution -----------------------------------------------------------
    def _execute_single(self, req: _Request) -> None:
        req.ticket.started_at = time.monotonic()
        try:
            with metrics.scoped() as qm:
                result = self._run_plan(req)
            req.ticket.metrics = qm.snapshot()
            self._finish(req.ticket, result=result)
        except Exception as e:  # noqa: BLE001 - one query's failure is its own
            self._finish(req.ticket, error=e)

    def _run_plan(self, req: _Request) -> ColumnarBatch:
        from ..exec.executor import Executor

        if self._host_latch.is_set():
            executor = Executor(self.session.conf, device=False, mesh=None)
        else:
            executor = Executor(self.session.conf, mesh=self.session.mesh)
        return executor.execute(req.plan)

    def _execute_batch(self, live: List[_Request]) -> None:
        now = time.monotonic()
        for r in live:
            r.ticket.started_at = now
        residents = [r.resident for r in live]
        try:
            # one scope for the whole coalesced dispatch + host legs:
            # batched tickets share their batch's metrics snapshot (a
            # per-query split of one stacked launch would be fiction)
            with metrics.scoped() as bm:
                results = batcher.execute_batch(residents)
        except Exception as e:  # noqa: BLE001 - device loss mid-serve
            # the wedge path: drop the table so no later query retries the
            # dead device, latch the server host-side, and serve THIS
            # batch from the host engine — identical results, no error
            # escapes to callers
            self._latch_host(repr(e), residents[0])
            results = None
        if results is None:
            if not self._host_latch.is_set():
                # stacked dispatch declined (not an error): per-query path
                metrics.incr("serve.batch.declined")
            for r in live:
                self._execute_single(r)
            return
        with self._cond:
            self._dispatches += 1
            self._batched_queries += len(live)
            n = len(live)
            self._batch_sizes[n] = self._batch_sizes.get(n, 0) + 1
        snap = bm.snapshot()
        for r, result in zip(live, results):
            r.ticket.batch_size = len(live)
            r.ticket.metrics = snap
            self._finish(r.ticket, result=result)

    def _latch_host(self, reason: str, resident) -> None:
        from ..exec.hbm_cache import hbm_cache
        from ..exec.mesh_cache import mesh_cache

        with self._cond:
            already = self._host_latch.is_set()
            self._host_latch.set()
            self._degraded_reason = self._degraded_reason or reason
        if not already:
            metrics.incr("serve.degraded")
            cache = mesh_cache if resident.mesh is not None else hbm_cache
            cache.drop(resident.table)

    def _miss_deadline(self, req: _Request) -> None:
        with self._cond:
            self._deadline_missed += 1
        metrics.incr("serve.deadline_missed")
        self._finish(
            req.ticket,
            error=DeadlineExceeded(
                "deadline expired while queued "
                f"(waited {time.monotonic() - req.ticket.submitted_at:.3f}s)."
            ),
        )

    def _finish(self, ticket: QueryTicket, result=None, error=None) -> None:
        ticket.finished_at = time.monotonic()
        ticket._result = result
        ticket._error = error
        if ticket.started_at is not None:
            service = ticket.finished_at - ticket.started_at
            with self._cond:
                self._ewma_service_s = (
                    0.8 * self._ewma_service_s + 0.2 * service
                )
                self._waits.append(ticket.wait_s or 0.0)
        with self._cond:
            if error is None:
                self._completed += 1
            else:
                self._failed += 1
            # latency percentiles describe SERVED queries: tickets that
            # never started (deadline-missed, plan-error, close()-shed)
            # would pollute p50/p99 with pure queue wait
            if ticket.started_at is not None and ticket.latency_s is not None:
                self._latencies.append(ticket.latency_s)
        if error is None:
            metrics.incr("serve.completed")
        ticket._done.set()

    # -- degradation surface -------------------------------------------------
    def _consult_device_latch(self) -> bool:
        """True when serving is latched host-side, consulting the
        process-wide deviceprobe first-touch verdict: a wedged device
        discovered by ANY component degrades serving without waiting for
        a serve-path failure. Called per submit (latched_verdict is one
        dict probe) and by the ``degraded`` property."""
        if self._host_latch.is_set():
            return True
        from ..utils.deviceprobe import latched_verdict

        if latched_verdict() is False:
            with self._cond:
                newly = not self._host_latch.is_set()
                self._host_latch.set()
                self._degraded_reason = (
                    self._degraded_reason or "deviceprobe first-touch verdict"
                )
            if newly:
                metrics.incr("serve.degraded")
            return True
        return False

    @property
    def degraded(self) -> bool:
        """True once the server latched onto the host engine (serve-path
        failure or deviceprobe first-touch verdict)."""
        return self._consult_device_latch()

    # -- observability -------------------------------------------------------
    def stats(self) -> dict:
        import statistics

        with self._cond:
            lat = sorted(self._latencies)
            waits = list(self._waits)
            out = {
                "submitted": self._submitted,
                "completed": self._completed,
                "failed": self._failed,
                "shed": self._shed,
                "deadline_missed": self._deadline_missed,
                "queue_depth": len(self._queue),
                "workers": len(self._workers),
                "degraded": self._host_latch.is_set(),
                "degraded_reason": self._degraded_reason,
                "batch_dispatches": self._dispatches,
                "batched_queries": self._batched_queries,
                "batch_size_hist": dict(sorted(self._batch_sizes.items())),
                "mean_batch_size": round(
                    self._batched_queries / self._dispatches, 2
                )
                if self._dispatches
                else None,
                "plan_cache": self.plan_cache.snapshot(),
                # join-region surface: what the resident join pipeline
                # holds (regions, bytes, generation) — operators read
                # this next to the serve counters to see whether
                # aggregate-joins are being served fused or host-side
                "join_regions": _join_region_stats(),
                # residency tier surface: per-table tier ladder state
                # (which rung each table landed on, compression ratio,
                # window counters) — operators read this to see whether
                # oversubscribed tables are serving compressed/streaming
                # or falling off to host
                "residency": _residency_stats(),
                # reliability surface: what the lifecycle layer absorbed
                # (retries) and healed (rollbacks) while this server ran
                # — THIS server's sweeps plus the process-wide counters
                "reliability": {
                    "server_recovery_sweeps": self._recovery_sweeps,
                    "recovered_indexes": self._recovered_indexes,
                    **reliability_snapshot(),
                },
            }
            if lat:
                out["latency_p50_ms"] = round(
                    1e3 * lat[len(lat) // 2], 3
                )
                out["latency_p99_ms"] = round(
                    1e3 * lat[min(len(lat) - 1, int(len(lat) * 0.99))], 3
                )
            if waits:
                out["mean_wait_ms"] = round(1e3 * statistics.fmean(waits), 3)
        return out


def _residency_stats() -> dict:
    """Tier-ladder snapshot for stats(): per-cache table tiers plus the
    process-wide counter family (telemetry.residency_snapshot) — the
    compact operator view; per-table detail stays on the cache
    snapshots for drill-down (docs/15-streaming-residency.md)."""
    from ..exec.hbm_cache import hbm_cache
    from ..exec.mesh_cache import mesh_cache
    from ..telemetry.metrics import residency_snapshot

    return {
        "hbm": hbm_cache.snapshot_residency(),
        "mesh": mesh_cache.snapshot_residency(),
        **residency_snapshot(),
    }


def _join_region_stats() -> dict:
    """Compact join-region residency snapshot for stats() — counts and
    generation only; the per-region detail stays on the cache snapshots
    (hbm_cache.snapshot_joins) for operators who drill down."""
    from ..exec.hbm_cache import hbm_cache
    from ..exec.mesh_cache import mesh_cache

    out = {}
    for name, cache in (("hbm", hbm_cache), ("mesh", mesh_cache)):
        snap = cache.snapshot_joins()
        out[name] = {
            "regions": snap["regions"],
            "mb": snap["mb"],
            "version": snap["version"],
        }
    return out
