"""QueryServer: multi-tenant concurrent query serving over one session.

The session API executes one query per ``collect()`` call on the calling
thread; the north star serves heavy concurrent traffic from many
tenants. This server puts per-tenant admission queues, a weighted-fair
dispatcher, and a worker pool between callers and the executor:

* **per-tenant admission control** — ``submit(df, tenant=...)`` routes
  through per-tenant quotas (queue-depth and in-flight caps, weights —
  the ``hyperspace.serve.tenant.*`` conf family) in front of the global
  bound, so one tenant's burst is shed at ITS door while everyone
  else's queries keep landing; ``AdmissionRejected`` carries the
  tenant, its depth, and a retry-after derived from the tenant's
  OBSERVED drain rate (queue depth / completions-per-second);
* **weighted-fair dispatch** — workers pull the next query via smooth
  weighted round-robin over the backlogged tenants (serve.tenancy), so
  completed-query shares converge to weight shares under contention
  instead of FIFO's arrival-order capture;
* **snapshot-pinned reads** — each admitted query pins the index-log
  version it admitted under (the plan-cache version token): the
  optimized plan bakes that snapshot's file identities in, so a
  concurrent refresh/optimize never tears a running query across two
  index generations — it serves wholly pre- or wholly post-refresh;
* **per-query deadlines** — a query whose deadline passes while queued
  is failed without executing; repeated misses open the tenant's
  CIRCUIT BREAKER (reject for a cooldown, then half-open: one probe
  decides), so a tenant that cannot make its deadlines stops adding
  queue wait for tenants that can;
* **micro-batching** — a worker that dequeues a batchable resident scan
  drains every compatible queued request (across tenants) and serves
  them with ONE device dispatch (serve.batcher);
* **graceful overload degradation** — a load-shed ladder as global
  occupancy climbs: lowest-weight tenants shed first, then micro-batch
  widening is disabled, and (on device failure, not load) the host
  latch serves exact host paths until restart. Latched beats flapping:
  the wedged-tunnel failure mode hangs, so each retry costs a timeout.

Tickets: ``submit()`` returns a QueryTicket immediately; ``result()``
blocks for that query only, ``cancel()`` withdraws it if still queued.
Worker threads execute each query under a scoped metrics child
(telemetry.metrics), so every ticket carries attributable counters.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..exceptions import HyperspaceException
from ..storage.columnar import ColumnarBatch
from ..telemetry.metrics import metrics, reliability_snapshot, serve_snapshot
from ..telemetry.recorder import flight_recorder
from ..telemetry.trace import QueryTrace, span
from . import batcher, tenancy
from .plan_cache import PlanCache
from .tenancy import DEFAULT_TENANT, CircuitBreaker, TenantState


class AdmissionRejected(HyperspaceException):
    """Admission refused. ``reason`` says which gate fired (queue_full /
    tenant_queue_full / shed_lowweight / breaker_open); ``retry_after_s``
    is derived from the tenant's observed drain rate where one exists."""

    def __init__(
        self,
        queue_depth: int,
        retry_after_s: float,
        tenant: Optional[str] = None,
        tenant_depth: Optional[int] = None,
        reason: str = "queue_full",
    ):
        super().__init__(
            f"admission rejected ({reason}): queue depth {queue_depth}"
            + (
                f", tenant {tenant!r} depth {tenant_depth}"
                if tenant is not None
                else ""
            )
            + f"; retry after ~{retry_after_s:.3f}s"
        )
        self.queue_depth = queue_depth
        self.retry_after_s = retry_after_s
        self.tenant = tenant
        self.tenant_depth = tenant_depth
        self.reason = reason


class DeadlineExceeded(HyperspaceException):
    pass


class QueryCancelled(HyperspaceException):
    """The ticket was withdrawn via cancel() before dispatch."""


class ServerClosed(HyperspaceException):
    pass


@dataclass
class ServeConfig:
    max_workers: int = 4
    # GLOBAL queue bound (sum across tenants); per-tenant caps come from
    # the hyperspace.serve.tenant.* conf family
    max_queue: int = 64
    # applied when submit() passes no deadline; None = no deadline
    default_deadline_s: Optional[float] = None
    # largest number of compatible queries one dispatch coalesces;
    # 1 disables micro-batch widening outright
    batch_max: int = 64
    plan_cache_entries: int = 256
    # tests construct paused servers (submit a burst, then start()) to
    # make coalescing deterministic; production keeps the default
    autostart: bool = True
    # how often the submit path consults crash recovery: at most one
    # background sweep per interval rolls back abandoned writers
    # (transient log head + expired lease) so a serving process heals
    # indexes a dead builder left wedged. <= 0 disables.
    recovery_sweep_interval_s: float = 60.0
    # how often the submit path may kick a background COMPACTION sweep
    # (index/compactor.py — runs-layout indexes converge toward per-
    # bucket files while the server keeps serving snapshot-pinned reads).
    # None = the hyperspace.index.compaction.intervalSeconds conf; <= 0
    # disables. Sweeps only run at all when the conf family enables
    # compaction (hyperspace.index.compaction.enabled=auto).
    compaction_sweep_interval_s: Optional[float] = None


class QueryTicket:
    """Handle for one submitted query. ``result()`` blocks until the
    server finishes it (or ``timeout`` passes — TimeoutError), then
    returns the ColumnarBatch or raises what execution raised.
    ``cancel()`` withdraws the query if it is still queued."""

    def __init__(self, deadline_at: Optional[float], tenant: str = DEFAULT_TENANT):
        self._done = threading.Event()
        self._result: Optional[ColumnarBatch] = None
        self._error: Optional[BaseException] = None
        self.submitted_at = time.monotonic()
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.deadline_at = deadline_at
        self.tenant = tenant
        # the index-log snapshot this query admitted under — the sorted
        # (name, id, state) tuple of ACTIVE indexes from the plan-cache
        # version token; the optimized plan serves exactly this snapshot
        self.pinned_log_version: Optional[tuple] = None
        self.batch_size = 1  # queries sharing this one's device dispatch
        self.metrics: Optional[dict] = None  # per-query scoped snapshot
        # per-query span trace (telemetry.trace.QueryTrace; None when
        # hyperspace.telemetry.tracing=off): admission -> queue-wait ->
        # dispatch -> D2H stage boundaries, finished and rung into the
        # flight recorder by _finish
        self.trace: Optional[QueryTrace] = None
        # server-side backrefs for cancel(); None once no longer queued
        self._server: Optional["QueryServer"] = None
        self._request: Optional["_Request"] = None
        self._tenant_state: Optional[TenantState] = None
        self._is_probe = False  # this submission is a breaker half-open probe

    def done(self) -> bool:
        return self._done.is_set()

    def cancel(self) -> bool:
        """Withdraw the query if it is still QUEUED: True when this call
        removed it (result() then raises QueryCancelled), False when it
        already dispatched, finished, or was never enqueued — dispatch
        and cancel race under the server lock, exactly one wins."""
        server = self._server
        if server is None or self._done.is_set():
            return False
        return server._cancel(self)

    def result(self, timeout: Optional[float] = None) -> ColumnarBatch:
        if not self._done.wait(timeout):
            raise TimeoutError("query still in flight")
        if self._error is not None:
            raise self._error
        return self._result

    @property
    def wait_s(self) -> Optional[float]:
        if self.started_at is None:
            return None
        return self.started_at - self.submitted_at

    @property
    def latency_s(self) -> Optional[float]:
        if self.finished_at is None:
            return None
        return self.finished_at - self.submitted_at


class _Request:
    __slots__ = (
        "df",
        "plan",
        "resident",
        "ticket",
        "tenant",
        "inflight_charged",
        "result_key",
    )

    def __init__(self, df, plan, resident, ticket, tenant, result_key=None):
        self.df = df
        self.plan = plan
        self.resident = resident  # Optional[batcher.ResidentScanRequest]
        self.ticket = ticket
        self.tenant = tenant  # TenantState
        # True once this request holds an in-flight slot; the worker's
        # finally decrements only charged requests, so a kill landing
        # between batch registration and the charge cannot corrupt the
        # tenant's in-flight accounting in either direction
        self.inflight_charged = False
        # RESULT-cache memo key (compile.result_cache) when the conf
        # enables it — a successful single execution stores under it
        self.result_key = result_key


class QueryServer:
    def __init__(self, session, config: Optional[ServeConfig] = None):
        self.session = session
        self.config = config or ServeConfig()
        self.plan_cache = PlanCache(self.config.plan_cache_entries)
        self._cond = threading.Condition()
        self._tenants: Dict[str, TenantState] = {}
        # O(1)/O(backlogged) admission bookkeeping (all under _cond): a
        # running global depth, the registered-weight summary, and the
        # set of tenants with queued work — admission and dispatch run
        # per query under the one lock, so O(all-tenants-ever-seen)
        # rescans there would serialize the serve tier at fleet scale
        # (tenants never deregister; idle ones must cost nothing)
        self._backlogged: Dict[str, TenantState] = {}
        self._depth = 0
        self._weight_set: set = set()
        self._min_weight: Optional[float] = None
        self._workers: List[threading.Thread] = []
        self._closed = False
        # conf-driven tenancy knobs, resolved once at construction (the
        # per-tenant policy itself resolves lazily at first submit so
        # conf edits before a tenant's first query apply to it)
        conf = session.conf
        self._breaker_miss_threshold = conf.serve_breaker_miss_threshold()
        self._breaker_open_s = conf.serve_breaker_open_seconds()
        self._shed_highwater = conf.serve_shed_highwater_fraction()
        self._shed_batch_off = conf.serve_shed_batch_off_fraction()
        self._drain_window_s = conf.serve_drain_rate_window_seconds()
        # host latch-down is an Event, not a lock-guarded bool: workers
        # consult it on every query's hot path, and an Event read is
        # race-free without taking _cond (the HS010 finding: the bool
        # was written under _cond but read lock-free in three places)
        self._host_latch = threading.Event()
        self._degraded_reason: Optional[str] = None
        # result-cache admission window: fingerprints seen at admission
        # decisions, sized by conf (serve/cache_policy — the repeat-rate
        # signal of the telemetry-driven admission rule)
        from .cache_policy import AdmissionWindow

        self._rc_window = AdmissionWindow(conf.compile_result_cache_window())
        # serving stats (guarded by _cond's lock)
        self._submitted = 0
        self._completed = 0
        self._failed = 0
        self._shed = 0
        self._rejected_breaker = 0
        self._cancelled = 0
        self._deadline_missed = 0
        self._dispatches = 0  # device dispatches for batched queries
        self._batched_queries = 0
        self._batch_sizes: Dict[int, int] = {}
        self._latencies: "deque[float]" = deque(maxlen=4096)
        self._waits: "deque[float]" = deque(maxlen=4096)
        self._ewma_service_s = 0.01
        # scheduler-turn log: which tenant each dispatch slot went to —
        # the fairness evidence stats()/bench config 15 score
        self._dispatch_order: "deque[str]" = deque(maxlen=4096)
        self._workers_killed = 0
        self._recovery_sweeps = 0
        self._recovered_indexes = 0
        self._next_recovery_sweep = 0.0  # monotonic; 0 = sweep on first submit
        self._compaction_sweeps = 0
        self._compaction_steps = 0
        self._next_compaction_sweep = 0.0
        self._compaction_running = False  # one sweep in flight at a time
        if self.config.autostart:
            self.start()

    # -- lifecycle -----------------------------------------------------------
    @property
    def closed(self) -> bool:
        return self._closed

    def start(self) -> "QueryServer":
        """Spawn the worker pool (idempotent)."""
        with self._cond:
            if self._closed:
                raise ServerClosed("query server is closed.")
            missing = self.config.max_workers - len(self._workers)
            for i in range(missing):
                t = threading.Thread(
                    target=self._worker_loop,
                    daemon=True,
                    name=f"hyperspace-serve-{len(self._workers)}",
                )
                self._workers.append(t)
                t.start()
        return self

    def close(self, timeout_s: float = 10.0) -> None:
        """Stop accepting work, fail queued queries with ServerClosed,
        and join the workers (in-flight queries finish)."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            pending: List[_Request] = []
            for t in self._tenants.values():
                pending.extend(t.queue)
                t.queue.clear()
            self._backlogged.clear()
            self._depth = 0
            self._cond.notify_all()
            workers = list(self._workers)
        for req in pending:
            self._finish(req.ticket, error=ServerClosed("server closed."))
        for t in workers:
            t.join(timeout_s)

    def ping(self) -> dict:
        """Lightweight liveness probe (the router's health director
        calls this before spending a real query on a probation probe):
        no queue, no planning — just the closed flag and pool size under
        the lock. Raises ServerClosed on a closed server so probes
        observe death exactly the way query legs do."""
        with self._cond:
            if self._closed:
                raise ServerClosed("query server is closed.")
            return {"workers": len(self._workers), "queue_depth": self._depth}

    # -- tenancy -------------------------------------------------------------
    def _tenant_locked(self, name: str) -> TenantState:
        t = self._tenants.get(name)
        if t is None:
            t = TenantState(
                name,
                self.session.conf.serve_tenant_policy(name),
                CircuitBreaker(
                    self._breaker_miss_threshold, self._breaker_open_s
                ),
                self._drain_window_s,
            )
            self._tenants[name] = t
            # tenants never deregister, so the weight summary only grows
            self._weight_set.add(t.policy.weight)
            if self._min_weight is None or t.policy.weight < self._min_weight:
                self._min_weight = t.policy.weight
        return t

    def _global_depth_locked(self) -> int:
        return self._depth

    def _shed_stage_locked(self) -> int:
        depth = self._global_depth_locked()
        cap = max(self.config.max_queue, 1)
        if depth >= self._shed_batch_off * cap:
            return 2
        if depth >= self._shed_highwater * cap:
            return 1
        return 0

    def _reject_locked(
        self, tenant: TenantState, reason: str, retry_after: Optional[float] = None
    ) -> AdmissionRejected:
        """Build (not raise) the rejection, with counters. Retry-after
        comes from the tenant's observed drain rate unless the gate
        supplies its own (breaker cooldown). Breaker rejections count
        as rejected_breaker, NOT shed — stats()["shed"] stays equal to
        the serve.shed counter and the per-tenant shed sum."""
        if reason == "breaker_open":
            self._rejected_breaker += 1
            tenant.rejected_breaker += 1
            metrics.incr("serve.breaker.rejected")
        else:
            self._shed += 1
            tenant.shed += 1
            metrics.incr("serve.shed")
            if reason == "shed_lowweight":
                metrics.incr("serve.shed.lowweight")
            # post-mortem: the FIRST shed of a storm freezes the flight
            # recorder (rate-limited per reason inside; capture is an
            # O(ring) deque copy, safe under _cond)
            flight_recorder.snapshot("shed")
        if retry_after is None:
            retry_after = tenant.retry_after_locked(self._ewma_retry_locked())
        return AdmissionRejected(
            self._global_depth_locked(),
            retry_after,
            tenant=tenant.name,
            tenant_depth=len(tenant.queue),
            reason=reason,
        )

    def _ewma_retry_locked(self) -> float:
        """Service-time fallback estimate for tenants with no windowed
        completions yet: backlog drained at EWMA service time across the
        worker pool."""
        backlog = self._global_depth_locked() / max(self.config.max_workers, 1)
        return max(backlog * self._ewma_service_s, 0.001)

    def _admit_locked(self, tenant: TenantState, ticket: QueryTicket) -> None:
        """Every admission gate, cheapest-rejection-first, called BEFORE
        plan optimization so an overloaded server sheds without paying
        the planner. Raises AdmissionRejected; marks probe tickets."""
        now = time.monotonic()
        admitted, retry_after = tenant.breaker.admit_locked(now)
        if not admitted:
            raise self._reject_locked(tenant, "breaker_open", retry_after)
        if tenant.breaker.probe_inflight and tenant.breaker.state == tenancy.HALF_OPEN:
            # admit_locked flipped probe_inflight for THIS submission
            # exactly when it returned the probe slot
            ticket._is_probe = True
        try:
            # load-shed ladder stage 1: lowest-weight tenant class first
            # — only meaningful when registered weights actually differ
            if (
                self._shed_stage_locked() >= 1
                and len(self._weight_set) > 1
                and tenant.policy.weight == self._min_weight
            ):
                raise self._reject_locked(tenant, "shed_lowweight")
            if len(tenant.queue) >= max(tenant.policy.max_queue, 1):
                raise self._reject_locked(tenant, "tenant_queue_full")
            if self._global_depth_locked() >= self.config.max_queue:
                raise self._reject_locked(tenant, "queue_full")
        except AdmissionRejected:
            # a probe that a LATER gate rejected never ran: free the
            # half-open slot so the next submission can probe
            if ticket._is_probe:
                tenant.breaker.probe_inflight = False
            raise
        if ticket._is_probe:
            tenant.breaker.note_probe_admitted_locked()

    # -- admission -----------------------------------------------------------
    def submit(
        self,
        df,
        deadline_s: Optional[float] = None,
        tenant: str = DEFAULT_TENANT,
    ) -> QueryTicket:
        """Enqueue a DataFrame for execution under ``tenant``'s quotas.
        Raises AdmissionRejected when a quota, the shed ladder, or the
        tenant's circuit breaker refuses (backpressure — the caller
        decides whether to retry, degrade, or shed; serve.client has the
        jittered-backoff helper), ServerClosed after close()."""
        if df.session is not self.session:
            raise HyperspaceException(
                "Cannot serve a DataFrame from a different session."
            )
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        deadline_at = (
            time.monotonic() + deadline_s if deadline_s is not None else None
        )
        # recovery consulted on submit: a throttled background sweep heals
        # indexes whose writer died (the serving process is often the only
        # long-lived process around to notice)
        self._maybe_recovery_sweep()
        # background compaction, hosted the same way: runs-layout indexes
        # converge toward per-bucket files while admitted queries keep
        # serving their pinned snapshots wholesale
        self._maybe_compaction_sweep()
        ticket = QueryTicket(deadline_at, tenant)
        ticket._server = self
        if self.session.conf.telemetry_tracing_enabled():
            ticket.trace = QueryTrace("serve.query", tenant=tenant)
        import contextlib

        tcm = (
            ticket.trace.activate()
            if ticket.trace is not None
            else contextlib.nullcontext()
        )
        with tcm:
            return self._submit_traced(df, ticket, tenant)

    def _submit_traced(self, df, ticket: QueryTicket, tenant: str) -> QueryTicket:
        # all admission gates run BEFORE planning: an overloaded or
        # breaker-open tenant is rejected for two dict probes, not a
        # full optimizer pass
        with span("serve.admission"):
            with self._cond:
                if self._closed:
                    raise ServerClosed("query server is closed.")
                tstate = self._tenant_locked(tenant)
                ticket._tenant_state = tstate
                self._admit_locked(tstate, ticket)
                # queue depth is a LEVEL (gauge), sampled per admission —
                # the load evidence next to the shed ladder's counters
                metrics.gauge("serve.queue_depth", self._depth)
        # plan + batchability resolved at submit time: the plan cache
        # makes repeats ~two dict probes, and classified requests let the
        # worker's coalescing scan stay a pure queue walk under the lock.
        # The version token PINS the index-log snapshot: the optimized
        # plan bakes this snapshot's files, so the query serves it
        # wholesale across any concurrent refresh/optimize.
        try:
            # the result-cache path shares ONE plan_signature walk with
            # the plan cache — the tree string + leaf snapshots must not
            # be computed twice per submission
            signature = None
            rc_enabled = self.session.conf.compile_result_cache_enabled()
            if rc_enabled:
                from .plan_cache import plan_signature

                signature = plan_signature(df.plan)
            with span("serve.plan"):
                plan, token = self.plan_cache.optimized_plan_with_token(
                    df, signature=signature
                )
            ticket.pinned_log_version = token[1]
            # RESULT cache (compile.result_cache, conf-gated off by
            # default): a value-level hit under the SAME pinned token
            # serves the memoized table without touching a worker —
            # sound because the key carries literals, file snapshots,
            # index generation, and conf. A snapshot-pinned reader hits
            # entries of ITS pinned token wholesale (never a newer
            # epoch): the token is part of the key, and old-token
            # entries are never proactively dropped on token change.
            # The device-loss latch BYPASSES the cache — no lookup, no
            # rc_key so no store — but never poisons it: entries stay,
            # and un-latching resumes hits (docs/17).
            rc_key = None
            latched = self._consult_device_latch()
            if rc_enabled and latched:
                metrics.incr("compile.result_cache.bypass_latched")
            elif rc_enabled:
                from ..compile.result_cache import result_cache, result_key

                rc_key = result_key(df.plan, token, signature=signature)
                with span("result_cache.lookup"):
                    cached = result_cache.get(rc_key)
                if cached is not None:
                    metrics.incr("serve.submitted")
                    with self._cond:
                        self._submitted += 1
                        tstate.submitted += 1
                    if ticket.trace is not None:
                        ticket.trace.root.labels["result_cache"] = "hit"
                    self._finish(ticket, result=cached)
                    return ticket
            resident = (
                None if latched else batcher.classify(self.session, plan)
            )
        except Exception as e:  # noqa: BLE001 - planning failure = query failure
            # planning failures (unknown columns, vanished files) belong
            # to the QUERY, not the server: the ticket carries them and
            # admission still succeeds (and counts as a submission, so
            # stats() can never report failed > submitted)
            metrics.incr("serve.plan_error")
            metrics.incr("serve.submitted")
            with self._cond:
                self._submitted += 1
                tstate.submitted += 1
            self._finish(ticket, error=e)
            return ticket
        req = _Request(df, plan, resident, ticket, tstate, rc_key)
        ticket._request = req
        with self._cond:
            if self._closed:
                raise ServerClosed("query server is closed.")
            # caps re-checked: concurrent submits may have filled the
            # queue while this one was planning
            try:
                if len(tstate.queue) >= max(tstate.policy.max_queue, 1):
                    raise self._reject_locked(tstate, "tenant_queue_full")
                if self._global_depth_locked() >= self.config.max_queue:
                    raise self._reject_locked(tstate, "queue_full")
            except AdmissionRejected:
                if ticket._is_probe:
                    # the already-counted probe lost the enqueue race:
                    # un-count it with the slot — it never ran
                    tstate.breaker.probe_inflight = False
                    tstate.breaker.probes -= 1
                    metrics.incr("serve.breaker.probe", -1)
                raise
            self._submitted += 1
            tstate.submitted += 1
            tstate.queue.append(req)
            self._backlogged[tenant] = tstate
            self._depth += 1
            self._cond.notify()
        metrics.incr("serve.submitted")
        return ticket

    def _cancel(self, ticket: QueryTicket) -> bool:
        """Remove ``ticket``'s request from its tenant queue if still
        queued; dispatch and cancel race under _cond, one wins."""
        with self._cond:
            req = ticket._request
            tstate = ticket._tenant_state
            if req is None or tstate is None or ticket._done.is_set():
                return False
            try:
                tstate.queue.remove(req)
            except ValueError:
                return False  # already dispatched (or close() drained it)
            self._depth -= 1
            if not tstate.queue:
                self._backlogged.pop(tstate.name, None)
        metrics.incr("serve.cancelled")
        self._finish(ticket, error=QueryCancelled("cancelled before dispatch."))
        return True

    def _maybe_recovery_sweep(self) -> None:
        interval = self.config.recovery_sweep_interval_s
        if interval is None or interval <= 0:
            return
        now = time.monotonic()
        with self._cond:
            if now < self._next_recovery_sweep:
                return
            self._next_recovery_sweep = now + interval
        threading.Thread(
            target=self._recovery_sweep, daemon=True, name="hyperspace-serve-recovery"
        ).start()

    def _maybe_compaction_sweep(self) -> None:
        if not self.session.conf.compaction_enabled():
            return
        interval = self.config.compaction_sweep_interval_s
        if interval is None:
            interval = self.session.conf.compaction_interval_seconds()
        if interval is None or interval <= 0:
            return
        now = time.monotonic()
        with self._cond:
            if now < self._next_compaction_sweep or self._compaction_running:
                return
            self._next_compaction_sweep = now + interval
            self._compaction_running = True
        threading.Thread(
            target=self._compaction_sweep,
            daemon=True,
            name="hyperspace-serve-compaction",
        ).start()

    def _compaction_sweep(self) -> None:
        from ..index.compactor import IndexCompactor

        try:
            results = IndexCompactor(self.session).sweep()
        except Exception:  # noqa: BLE001
            # counted, not raised: a failed sweep must never take down
            # serving — the next interval retries
            metrics.incr("serve.compaction_sweep_error")
            results = {}
        finally:
            with self._cond:
                self._compaction_running = False
        metrics.incr("serve.compaction_sweep")
        steps = sum(r.get("steps", 0) for r in results.values())
        with self._cond:
            self._compaction_sweeps += 1
            self._compaction_steps += steps

    def _recovery_sweep(self) -> None:
        from ..reliability.recovery import recover_abandoned_indexes

        try:
            n = recover_abandoned_indexes(
                self.session.conf.system_path(), self.session.conf
            )
        except Exception:  # noqa: BLE001
            # counted, not raised: a failed sweep must never take down
            # serving — the next interval retries
            metrics.incr("serve.recovery_sweep_error")
            return
        metrics.incr("serve.recovery_sweep")
        with self._cond:
            self._recovery_sweeps += 1
            self._recovered_indexes += n
        if n:
            # recovered indexes changed the log: cached plans may bind to
            # rolled-back versions, and the TTL catalog cache may hold
            # the transient view
            self.session.collection_manager.clear_cache()

    # -- worker --------------------------------------------------------------
    def _worker_loop(self) -> None:
        try:
            self._worker_loop_inner()
        except BaseException:  # noqa: BLE001 - worker killed mid-query
            # a BaseException (injected crash, interpreter teardown)
            # killed this worker; its in-flight tickets were already
            # failed by the execute paths' guards. Replace the worker so
            # the pool keeps serving, then die visibly.
            with self._cond:
                me = threading.current_thread()
                if me in self._workers:
                    self._workers.remove(me)
                self._workers_killed += 1
                closed = self._closed
            metrics.incr("serve.worker_killed")
            if not closed:
                try:
                    self.start()
                except ServerClosed:
                    # close() won the race since the snapshot above: no
                    # replacement needed, and the ORIGINAL kill cause
                    # must stay the exception this thread dies with
                    metrics.incr("serve.worker.respawn_declined")
            raise

    def _worker_loop_inner(self) -> None:
        while True:
            # batch accumulates INSIDE the guarded region: a kill landing
            # anywhere after a request is popped (even mid-drain, before
            # execution starts) must still resolve every popped ticket
            # and return its in-flight slot — popped requests have no
            # other owner who could ever pick them up again
            batch: List[_Request] = []
            try:
                with self._cond:
                    while not self._closed:
                        if self._next_request_locked(batch):
                            break
                        self._cond.wait()
                    if not batch:  # closed and drained
                        return
                    head = batch[0]
                    if (
                        head.resident is not None
                        and self.config.batch_max > 1
                        and not self._host_latch.is_set()
                        and self._shed_stage_locked() < 2
                    ):
                        self._drain_compatible_locked(head, batch)
                now = time.monotonic()
                live: List[_Request] = []
                for r in batch:
                    if (
                        r.ticket.deadline_at is not None
                        and now > r.ticket.deadline_at
                    ):
                        self._miss_deadline(r)
                    else:
                        live.append(r)
                if live:
                    if len(live) == 1 or live[0].resident is None:
                        for r in live:
                            self._execute_single(r)
                    else:
                        self._execute_batch(live)
            except BaseException as e:  # worker killed: resolve the batch
                for r in batch:
                    if not r.ticket.done():
                        self._finish(r.ticket, error=e)
                raise
            finally:
                if batch:
                    with self._cond:
                        capped = False
                        for r in batch:
                            if r.inflight_charged:
                                r.inflight_charged = False
                                r.tenant.inflight -= 1
                                if r.tenant.policy.inflight_cap() is not None:
                                    capped = True
                        # wake workers ONLY when headroom was actually
                        # freed under a finite cap — with no caps,
                        # completions never unblock anyone, and a
                        # broadcast per dispatch would cost O(workers)
                        # spurious round-trips on the serializing lock
                        if capped:
                            self._cond.notify_all()

    def _next_request_locked(self, batch: List[_Request]) -> bool:
        """The weighted-fair pick: next backlogged tenant with in-flight
        headroom via smooth WRR, then ITS oldest request (FIFO within a
        tenant preserves per-client ordering). The popped request is
        registered in ``batch`` BEFORE its in-flight slot is charged, so
        the worker's resolve-all/decharge guards stay consistent no
        matter where a kill lands. True when a request was taken."""
        t = tenancy.pick_tenant_locked(self._backlogged)
        if t is None:
            return False
        req = t.queue.popleft()
        batch.append(req)
        self._depth -= 1
        if not t.queue:
            del self._backlogged[t.name]
        t.inflight += 1
        req.inflight_charged = True
        self._dispatch_order.append(t.name)
        return True

    def _drain_compatible_locked(
        self, head: _Request, batch: List[_Request]
    ) -> None:
        """Pull every queued request sharing ``head``'s batch key (same
        resident table identity + resident column set) ACROSS backlogged
        tenants into ``batch`` — coalesced queries ride one dispatch, so
        widening the batch costs the batch nothing and saves each rider
        a round trip. Per-tenant queue order is preserved; per-tenant
        in-flight caps are honored. Called with the lock held."""
        key = head.resident.batch_key
        budget = self.config.batch_max - len(batch)
        # head's tenant first (its own burst is the common case), then
        # the other backlogged tenants in registration order —
        # deterministic for tests; idle tenants cost nothing
        tenants = [head.tenant] + [
            t for t in self._backlogged.values() if t is not head.tenant
        ]
        for t in tenants:
            if budget <= 0:
                break
            cap = t.policy.inflight_cap()
            if (cap is not None and t.inflight >= cap) or not t.queue:
                continue  # nothing takable: skip the O(queue) walk
            keep: "deque[_Request]" = deque()
            while t.queue and budget > 0:
                r = t.queue.popleft()
                if (
                    r.resident is not None
                    and r.resident.batch_key == key
                    and (cap is None or t.inflight < cap)
                ):
                    batch.append(r)
                    self._depth -= 1
                    t.inflight += 1
                    r.inflight_charged = True
                    budget -= 1
                else:
                    keep.append(r)
            keep.extend(t.queue)
            t.queue.clear()
            t.queue.extend(keep)
            if not t.queue:
                self._backlogged.pop(t.name, None)

    # -- execution -----------------------------------------------------------
    def _execute_single(self, req: _Request) -> None:
        import contextlib

        req.ticket.started_at = time.monotonic()
        tr = req.ticket.trace
        if tr is not None and tr.find("serve.queue_wait") is None:
            # the ticket's wait, as a span with explicit monotonic ends
            # (submit and dispatch run on different threads by design);
            # skipped when the batch path already recorded it — declined
            # or failed batches fall back through here per rider
            tr.add_span(
                "serve.queue_wait",
                req.ticket.submitted_at,
                req.ticket.started_at,
            )
        tcm = tr.activate() if tr is not None else contextlib.nullcontext()
        try:
            t0 = time.monotonic()
            with tcm, span("serve.execute", tenant=req.ticket.tenant):
                with metrics.scoped() as qm:
                    result = self._run_plan(req)
            wall_s = time.monotonic() - t0
            req.ticket.metrics = qm.snapshot()
            if req.result_key is not None:
                # the memo is best-effort: a store failure (bad conf
                # value, exotic batch) must NEVER convert an already-
                # successful query into a caller-visible error
                try:
                    self._store_result(req, result, wall_s)
                except Exception:  # noqa: BLE001 - memo only, counted
                    metrics.incr("compile.result_cache.store_error")
            self._finish(req.ticket, result=result)
        except Exception as e:  # noqa: BLE001 - one query's failure is its own
            self._finish(req.ticket, error=e)
        except BaseException as e:  # worker being killed: resolve the ticket
            self._finish(req.ticket, error=e)
            raise

    def _store_result(self, req: _Request, result, wall_s: float) -> None:
        """Telemetry-driven admission (docs/17): observe the query's
        structural fingerprint in the sliding window, price its observed
        recompute cost (trace spans when tracing is on, the direct
        dispatch wall otherwise), and let the cache decide."""
        from ..compile.fingerprint import batch_fingerprint
        from ..compile.result_cache import (
            budget_share_bytes,
            result_cache,
            result_roots,
        )
        from .cache_policy import recompute_cost_s

        conf = self.session.conf
        repeats = self._rc_window.observe(
            batch_fingerprint(req.plan), conf.compile_result_cache_window()
        )
        result_cache.put(
            req.result_key,
            result,
            result_roots(req.plan),
            conf.compile_result_cache_entries(),
            conf.compile_result_cache_max_bytes(),
            cost_s=recompute_cost_s(req.ticket.trace, wall_s),
            repeats=repeats,
            byte_rate=conf.compile_result_cache_byte_rate(),
            total_max_bytes=budget_share_bytes(
                conf.compile_result_cache_budget_share()
            ),
        )

    def _run_plan(self, req: _Request) -> ColumnarBatch:
        from ..exec.executor import Executor

        if self._host_latch.is_set():
            executor = Executor(self.session.conf, device=False, mesh=None)
        else:
            executor = Executor(self.session.conf, mesh=self.session.mesh)
        # the ticket's pinned index-log snapshot folds into the compiled-
        # pipeline cache key: a query admitted under version V serves V's
        # whole compiled pipeline across any concurrent refresh/optimize
        out = executor.execute(
            req.plan, version_token=req.ticket.pinned_log_version
        )
        tr = req.ticket.trace
        if tr is not None:
            p = executor.last_pipeline
            tr.meta["pipeline"] = p.describe() if p is not None else None
        return out

    def _execute_batch(self, live: List[_Request]) -> None:
        import contextlib

        now = time.monotonic()
        for r in live:
            r.ticket.started_at = now
            if r.ticket.trace is not None:
                r.ticket.trace.add_span(
                    "serve.queue_wait", r.ticket.submitted_at, now
                )
        residents = [r.resident for r in live]
        # the coalesced dispatch records under the HEAD ticket's trace;
        # riders adopt the shared span subtree afterwards (a per-rider
        # split of one stacked launch would be fiction — the batched-
        # metrics rule applied to spans)
        head_tr = live[0].ticket.trace
        tcm = (
            head_tr.activate() if head_tr is not None else contextlib.nullcontext()
        )
        batch_span = None
        try:
            # one scope for the whole coalesced dispatch + host legs:
            # batched tickets share their batch's metrics snapshot (a
            # per-query split of one stacked launch would be fiction)
            with tcm, span(
                "serve.batch_dispatch", batch=len(live)
            ) as batch_span:
                with metrics.scoped() as bm:
                    results = batcher.execute_batch(residents)
        except Exception as e:  # noqa: BLE001 - device loss mid-serve
            # the wedge path: drop the table so no later query retries the
            # dead device, latch the server host-side, and serve THIS
            # batch from the host engine — identical results, no error
            # escapes to callers. The failing span is already marked in
            # the head trace; the recorder snapshot captures the batch's
            # in-flight traces around the failure.
            self._latch_host(
                repr(e),
                residents[0],
                traces=[r.ticket.trace for r in live],
            )
            results = None
        except BaseException as e:  # worker being killed: resolve every ticket
            for r in live:
                if not r.ticket.done():
                    self._finish(r.ticket, error=e)
            raise
        if results is None:
            if not self._host_latch.is_set():
                # stacked dispatch declined (not an error): per-query path
                metrics.incr("serve.batch.declined")
            try:
                for r in live:
                    self._execute_single(r)
            except BaseException as e:  # worker killed mid-fallback: the
                # remaining riders were already popped from their queues
                # and no worker can re-pick them — resolve every one
                for r in live:
                    if not r.ticket.done():
                        self._finish(r.ticket, error=e)
                raise
            return
        with self._cond:
            self._dispatches += 1
            self._batched_queries += len(live)
            # per-tenant twin counted HERE, over the same post-filter
            # batch the global counter sees, so the per-tenant sum
            # always reconciles with stats()["batched_queries"]
            for r in live:
                r.tenant.batched_queries += 1
            n = len(live)
            self._batch_sizes[n] = self._batch_sizes.get(n, 0) + 1
        snap = bm.snapshot()
        for r, result in zip(live, results):
            r.ticket.batch_size = len(live)
            r.ticket.metrics = snap
            tr = r.ticket.trace
            if tr is not None and tr is not head_tr and batch_span is not None:
                tr.adopt(batch_span)
            self._finish(r.ticket, result=result)

    def _latch_host(self, reason: str, resident, traces=None) -> None:
        from ..exec.hbm_cache import hbm_cache
        from ..exec.mesh_cache import mesh_cache

        with self._cond:
            already = self._host_latch.is_set()
            self._host_latch.set()
            self._degraded_reason = self._degraded_reason or reason
        # post-mortem: freeze the flight recorder around the loss, with
        # the failing dispatch's in-flight traces attached (their failed
        # span is already marked error)
        flight_recorder.snapshot("device_loss", extra_traces=traces or ())
        if not already:
            metrics.incr("serve.degraded")
            cache = mesh_cache if resident.mesh is not None else hbm_cache
            cache.drop(resident.table)

    def _miss_deadline(self, req: _Request) -> None:
        metrics.incr("serve.deadline_missed")
        self._finish(
            req.ticket,
            error=DeadlineExceeded(
                "deadline expired while queued "
                f"(waited {time.monotonic() - req.ticket.submitted_at:.3f}s)."
            ),
        )

    def _finish(self, ticket: QueryTicket, result=None, error=None) -> None:
        ticket.finished_at = time.monotonic()
        ticket._result = result
        ticket._error = error
        ticket._request = None  # no longer cancellable
        tstate = ticket._tenant_state
        with self._cond:
            if ticket.started_at is not None:
                service = ticket.finished_at - ticket.started_at
                self._ewma_service_s = (
                    0.8 * self._ewma_service_s + 0.2 * service
                )
                self._waits.append(ticket.wait_s or 0.0)
            now = time.monotonic()
            if error is None:
                self._completed += 1
                if tstate is not None:
                    tstate.note_completion_locked(
                        now,
                        ticket.latency_s if ticket.started_at is not None else None,
                    )
                    # breaker: a probe success closes the circuit; a
                    # success while OPEN (admitted pre-open) only clears
                    # the consecutive-miss streak — the cooldown stands
                    if ticket._is_probe or tstate.breaker.state == tenancy.CLOSED:
                        tstate.breaker.record_success_locked()
                    else:
                        tstate.breaker.consecutive_misses = 0
            elif isinstance(error, QueryCancelled):
                self._cancelled += 1
                if tstate is not None:
                    tstate.cancelled += 1
                    if ticket._is_probe:
                        # a cancelled probe never decided anything: free
                        # the half-open slot or the breaker wedges —
                        # every later submission rejected forever
                        tstate.breaker.probe_inflight = False
            else:
                self._failed += 1
                if tstate is not None:
                    tstate.failed += 1
                    if isinstance(error, DeadlineExceeded):
                        self._deadline_missed += 1
                        tstate.deadline_missed += 1
                        tstate.breaker.record_miss_locked(
                            now, probe=ticket._is_probe
                        )
                    elif ticket._is_probe:
                        # probe died of an execution error, not a miss:
                        # inconclusive — free the probe slot for the next
                        tstate.breaker.probe_inflight = False
            # latency percentiles describe SERVED queries: tickets that
            # never started (deadline-missed, plan-error, close()-shed)
            # would pollute p50/p99 with pure queue wait
            if ticket.started_at is not None and ticket.latency_s is not None:
                self._latencies.append(ticket.latency_s)
        if error is None:
            metrics.incr("serve.completed")
        # latency/wait histograms describe SERVED queries, same rule as
        # the percentile reservoirs above
        if ticket.started_at is not None and ticket.latency_s is not None:
            metrics.observe("serve.latency_seconds", ticket.latency_s)
            metrics.observe("serve.wait_seconds", ticket.wait_s or 0.0)
        tr = ticket.trace
        if tr is not None:
            # the ticket's trace is the one attribution record: serve
            # identity, scoped metrics, and (set by _run_plan) the
            # compiled pipeline — explain(verbose) renders from it
            tr.meta["serve"] = {
                "tenant": ticket.tenant,
                "pinned_log_version": ticket.pinned_log_version,
            }
            if ticket.metrics is not None:
                tr.meta["metrics"] = ticket.metrics
            tr.finish(error)
            flight_recorder.record(tr)
            if error is None:
                self.session.last_trace = tr
        elif error is None:
            # tracing off: clear the attribution rather than let
            # explain(verbose) describe a previous query as this one
            self.session.last_trace = None
        ticket._done.set()

    # -- degradation surface -------------------------------------------------
    def _consult_device_latch(self) -> bool:
        """True when serving is latched host-side, consulting the
        process-wide deviceprobe first-touch verdict: a wedged device
        discovered by ANY component degrades serving without waiting for
        a serve-path failure. Called per submit (latched_verdict is one
        dict probe) and by the ``degraded`` property."""
        if self._host_latch.is_set():
            return True
        from ..utils.deviceprobe import latched_verdict

        if latched_verdict() is False:
            with self._cond:
                newly = not self._host_latch.is_set()
                self._host_latch.set()
                self._degraded_reason = (
                    self._degraded_reason or "deviceprobe first-touch verdict"
                )
            if newly:
                metrics.incr("serve.degraded")
            return True
        return False

    @property
    def degraded(self) -> bool:
        """True once the server latched onto the host engine (serve-path
        failure or deviceprobe first-touch verdict)."""
        return self._consult_device_latch()

    # -- observability -------------------------------------------------------
    def stats(self) -> dict:
        import statistics

        # copy raw reservoirs and scalars under the lock; sort/aggregate
        # AFTER releasing it — a telemetry loop polling stats() must not
        # stall admission and dispatch, which serialize on this lock
        with self._cond:
            lats = list(self._latencies)
            waits = list(self._waits)
            order = list(self._dispatch_order)
            tenants_raw = {
                name: (t.snapshot_locked(), list(t.latencies))
                for name, t in sorted(self._tenants.items())
            }
            shed_stage = self._shed_stage_locked()
            sweeps = self._recovery_sweeps
            recovered = self._recovered_indexes
            compaction_sweeps = self._compaction_sweeps
            compaction_steps = self._compaction_steps
            out = {
                "submitted": self._submitted,
                "completed": self._completed,
                "failed": self._failed,
                "shed": self._shed,
                "rejected_breaker": self._rejected_breaker,
                "cancelled": self._cancelled,
                "deadline_missed": self._deadline_missed,
                "queue_depth": self._global_depth_locked(),
                "workers": len(self._workers),
                "workers_killed": self._workers_killed,
                "degraded": self._host_latch.is_set(),
                "degraded_reason": self._degraded_reason,
                "batch_dispatches": self._dispatches,
                "batched_queries": self._batched_queries,
                "batch_size_hist": dict(sorted(self._batch_sizes.items())),
                "mean_batch_size": round(
                    self._batched_queries / self._dispatches, 2
                )
                if self._dispatches
                else None,
            }
        # the multi-tenant surface: per-tenant quotas, depths, p50/p99,
        # shed/rejected counters, breaker states — what an operator
        # reads to see WHO is loading the server and who is being
        # protected from whom
        tenants = {}
        for name, (snap, tl) in tenants_raw.items():
            snap.update(tenancy.latency_percentiles_ms(tl))
            tenants[name] = snap
        out["tenants"] = tenants
        dispatch_share: Dict[str, int] = {}
        for name in order:
            dispatch_share[name] = dispatch_share.get(name, 0) + 1
        # load-shed ladder position + the scheduler-turn shares behind
        # the fairness bound (window: last 4096 turns); widening is OFF
        # under the host latch too — every post-latch dispatch is
        # single-query regardless of the ladder
        out["overload"] = {
            "shed_stage": shed_stage,
            "batch_widening": shed_stage < 2
            and self.config.batch_max > 1
            and not self._host_latch.is_set(),
            "dispatch_share": dispatch_share,
        }
        # process-wide serve counter family (telemetry.metrics)
        out["serve_counters"] = serve_snapshot()
        out["plan_cache"] = self.plan_cache.snapshot()
        # whole-plan compilation surface: the compiled-pipeline cache,
        # the result cache, and the compile.* counter family — whether
        # bursts are reusing pipelines or re-lowering per query
        # (docs/17-plan-compilation.md)
        out["compile"] = _compile_stats()
        # result-cache surface: occupancy + bytes + the admission/
        # eviction counter family (telemetry.result_cache_snapshot) —
        # what the admission policy admitted, declined, and shed
        out["result_cache"] = _result_cache_stats()
        # join-region surface: what the resident join pipeline holds
        # (regions, bytes, generation) — operators read this next to the
        # serve counters to see whether aggregate-joins are being served
        # fused or host-side
        out["join_regions"] = _join_region_stats()
        # residency tier surface: per-table tier ladder state (which
        # rung each table landed on, compression ratio, window counters)
        out["residency"] = _residency_stats()
        # reliability surface: what the lifecycle layer absorbed
        # (retries) and healed (rollbacks) while this server ran — THIS
        # server's sweeps plus the process-wide counters
        out["reliability"] = {
            "server_recovery_sweeps": sweeps,
            "recovered_indexes": recovered,
            **reliability_snapshot(),
        }
        # background-compaction surface: THIS server's hosted sweeps and
        # the steps they committed (the process-wide compaction.* counter
        # family rides the registry export below)
        out["compaction"] = {
            "server_compaction_sweeps": compaction_sweeps,
            "compaction_steps": compaction_steps,
        }
        out.update(tenancy.latency_percentiles_ms(lats))
        if waits:
            out["mean_wait_ms"] = round(1e3 * statistics.fmean(waits), 3)
        # exporter surface (telemetry/export.py): the WHOLE registry as
        # Prometheus text + JSON-lines, for scrapes that read stats()
        # over an RPC shim; with hyperspace.telemetry.export.dir set,
        # each stats() call also appends a rotated on-disk snapshot
        # (failures counted, never raised — telemetry must not take
        # down serving)
        from ..telemetry import export as texport

        exp = {
            "prometheus": texport.render_prometheus(),
            "jsonl": texport.render_jsonl(),
            "recorder": {
                "traces": len(flight_recorder.last()),
                "snapshots": len(flight_recorder.snapshots()),
            },
            "written_to": None,
        }
        exp_dir = self.session.conf.telemetry_export_dir()
        if exp_dir:
            try:
                exp["written_to"] = str(
                    texport.export_to_dir(
                        exp_dir,
                        self.session.conf.telemetry_export_rotate_bytes(),
                        self.session.conf.telemetry_export_keep(),
                    )
                )
            except OSError:
                metrics.incr("telemetry.export.write_error")
        out["export"] = exp
        return out


def _compile_stats() -> dict:
    """Whole-plan-compilation snapshot for stats(): pipeline/result cache
    occupancy plus the process-wide compile.* counter family
    (telemetry.compile_snapshot)."""
    from ..compile.cache import pipeline_cache
    from ..compile.result_cache import result_cache
    from ..telemetry.metrics import compile_snapshot

    return {
        "pipelines": pipeline_cache.snapshot(),
        "results": result_cache.snapshot(),
        **compile_snapshot(),
    }


def _result_cache_stats() -> dict:
    """Result-cache snapshot for stats(): serve-level + router-level
    occupancy and the full admission/eviction counter families
    (telemetry.result_cache_snapshot)."""
    from ..compile.result_cache import result_cache, router_result_cache
    from ..telemetry.metrics import result_cache_snapshot

    return {
        "serve": result_cache.snapshot(),
        "router": router_result_cache.snapshot(),
        **result_cache_snapshot(),
    }


def _residency_stats() -> dict:
    """Tier-ladder snapshot for stats(): per-cache table tiers plus the
    process-wide counter family (telemetry.residency_snapshot) — the
    compact operator view; per-table detail stays on the cache
    snapshots for drill-down (docs/15-streaming-residency.md)."""
    from ..exec.hbm_cache import hbm_cache
    from ..exec.mesh_cache import mesh_cache
    from ..telemetry.metrics import residency_snapshot

    return {
        "hbm": hbm_cache.snapshot_residency(),
        "mesh": mesh_cache.snapshot_residency(),
        **residency_snapshot(),
    }


def _join_region_stats() -> dict:
    """Compact join-region residency snapshot for stats() — counts and
    generation only; the per-region detail stays on the cache snapshots
    (hbm_cache.snapshot_joins) for operators who drill down."""
    from ..exec.hbm_cache import hbm_cache
    from ..exec.mesh_cache import mesh_cache

    out = {}
    for name, cache in (("hbm", hbm_cache), ("mesh", mesh_cache)):
        snap = cache.snapshot_joins()
        out[name] = {
            "regions": snap["regions"],
            "mb": snap["mb"],
            "version": snap["version"],
        }
    return out
