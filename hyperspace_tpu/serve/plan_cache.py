"""Cross-query plan cache: repeat queries skip rewrite and ranking.

Under serving, the optimizer batch (normalization + the three Hyperspace
rules, including candidate enumeration and ranking against every ACTIVE
index) runs per query even when the fleet sends the same handful of
query shapes thousands of times. The rewrite is a pure function of

  * the NORMALIZED user plan — the logical tree with every literal,
    projection and the leaf relations' concrete file snapshot (name,
    size, mtime per file) baked into the signature, so a source that
    gained or lost files since the cached entry can never collide with
    it (Hybrid Scan decisions depend on exactly that snapshot);
  * the session's rewrite-relevant state — hyperspace on/off and the
    full conf (hybrid-scan flags etc. live there);
  * the index collection's LOG VERSION — (name, log id, state) of every
    ACTIVE stable index. Any create/refresh/optimize/delete bumps an id
    or changes the set, so cached plans from the previous index
    generation miss naturally and age out of the LRU.

The cache stores the OPTIMIZED logical plan (immutable — plan.ir nodes
are frozen dataclasses), not results. Entries are LRU-bounded; the
version enumeration rides the collection manager's TTL cache, so a
lookup costs two dict probes and no directory walk in steady state.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Tuple

from ..plan.ir import LogicalPlan, Scan
from ..telemetry import trace
from ..telemetry.metrics import metrics


def plan_signature(plan: LogicalPlan) -> Tuple:
    """Value-based signature of a user plan: the tree string (operators,
    expressions, literals, projections) plus every leaf relation's file
    identity snapshot — tree_string alone shows only file COUNTS, which
    two different snapshots can share."""
    leaves = []
    for node in plan.collect(lambda n: isinstance(n, Scan)):
        rel = node.relation
        leaves.append(
            (
                rel.file_format,
                tuple(rel.root_paths),
                tuple(sorted(rel.options.items())),
                tuple(
                    (f.name, f.size, f.modified_time) for f in rel.files
                ),
            )
        )
    return (plan.tree_string(), tuple(leaves))


class PlanCache:
    """Bounded LRU over (plan signature, session rewrite state, index log
    version) -> optimized plan."""

    def __init__(self, max_entries: int = 256):
        self._max = max(int(max_entries), 1)
        self._lock = threading.Lock()
        self._plans: "OrderedDict[tuple, LogicalPlan]" = OrderedDict()

    def _version_token(self, session) -> Tuple:
        from ..actions import states
        from ..exec.hbm_cache import hbm_cache
        from ..exec.mesh_cache import mesh_cache

        entries = session.collection_manager.get_indexes(
            [states.ACTIVE], prefer_stable=True
        )
        return (
            session.is_hyperspace_enabled(),
            tuple(sorted((e.name, e.id, e.state) for e in entries)),
            tuple(sorted((k, str(v)) for k, v in session.conf.as_dict().items())),
            # join-region generation: batch classification runs against
            # the optimized plan, so a cached plan must not outlive the
            # region generation it was classified under (register /
            # evict / invalidate / reset all bump these counters)
            (
                hbm_cache.join_region_version(),
                mesh_cache.join_region_version(),
            ),
        )

    def optimized_plan(self, df) -> LogicalPlan:
        """The optimized plan for ``df`` — cached when this exact plan was
        optimized under the same index-log version and session state.
        Cache hits skip rewrite AND usage-event telemetry (the event
        already fired when the plan was first optimized; serving metrics
        count executions)."""
        return self.optimized_plan_with_token(df)[0]

    def optimized_plan_with_token(
        self, df, signature: "Tuple" = None
    ) -> "Tuple[LogicalPlan, Tuple]":
        """``(optimized plan, version token)`` — the token is the exact
        index-log/session snapshot the plan was resolved under; the
        server pins it on the ticket so a query admitted under version V
        serves V wholesale across any concurrent refresh/optimize
        (token[1] is the sorted (name, id, state) tuple of ACTIVE
        indexes — the human-readable log version).

        Token and optimization are NOT naturally atomic: a refresh
        committing between the token read and the rewrite would bake the
        NEW generation's files into a plan pinned (and cached) under the
        OLD token — the pin would lie and the cache would serve the
        wrong generation to same-token callers. So the token is re-read
        after optimizing and the pair is only trusted (and cached) when
        both reads agree; a mismatch re-resolves under the new version.

        ``signature``: a caller-precomputed ``plan_signature(df.plan)``
        (the server's result-cache path already built one — the tree
        walk must not run twice per submission)."""
        if signature is None:
            signature = plan_signature(df.plan)
        token = self._version_token(df.session)
        for _attempt in range(4):
            key = (signature, token)
            with self._lock:
                hit = self._plans.get(key)
                if hit is not None:
                    self._plans.move_to_end(key)
            if hit is not None:
                metrics.incr("serve.plan_cache.hit")
                trace.annotate(plan_cache="hit")
                return hit, token
            metrics.incr("serve.plan_cache.miss")
            trace.annotate(plan_cache="miss")
            plan = df.optimized_plan(log_usage=True)
            token_after = self._version_token(df.session)
            if token_after == token:
                with self._lock:
                    self._plans[key] = plan
                    while len(self._plans) > self._max:
                        self._plans.popitem(last=False)
                return plan, token
            metrics.incr("serve.plan_cache.version_race")
            token = token_after
        # index log churning faster than we can replan (pathological):
        # REFUSE rather than pin a generation the double-read never
        # confirmed — a lying pin would serve torn snapshots silently.
        # The error rides the ticket as a plan failure; the client
        # retries into a (momentarily) quieter log.
        from ..exceptions import HyperspaceException

        raise HyperspaceException(
            "index log version changed on every replan attempt; could "
            "not resolve a stable snapshot to pin."
        )

    def snapshot(self) -> dict:
        with self._lock:
            return {"entries": len(self._plans), "max_entries": self._max}

    def clear(self) -> None:
        with self._lock:
            self._plans.clear()
