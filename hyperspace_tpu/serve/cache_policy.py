"""Telemetry-driven admission policy for the result caches (docs/17).

The PR-11 trace spans already record, per query, exactly what a result
cache needs to decide whether memoizing is worth the bytes: the observed
recompute cost (the dispatch + D2H wall inside ``compile.pipeline_run``
/ ``query.interpret``) and the structural ``batch_fingerprint`` whose
repeat rate predicts whether the SAME shape of work will come back.

The one decision rule, shared by the serve-level and router-level
caches:

    admit  iff  cost_s * repeats * byte_rate >= nbytes

— a cached byte "pays for itself" when the seconds it saves, scaled by
how often this fingerprint has been seen lately, exceed its storage
cost at the configured exchange rate (bytes-per-second-saved). A
fingerprint seen for the FIRST time in the window always declines
(``declined_cold``): cold structures are exactly the queries a cache
cannot help, and admitting them would let one-shot scans churn the
GDSF heap.

``AdmissionWindow`` is the sliding window of fingerprints seen at
admission time. It is deliberately NOT per-key: repeat rate is a
property of the query *structure* (literals vary, shape repeats), which
is why it keys on ``batch_fingerprint`` and not on the value-level
result key.
"""

from __future__ import annotations

import threading
from collections import Counter, deque
from typing import Optional


class AdmissionWindow:
    """Sliding window of fingerprints observed at admission decisions.

    ``observe(fp)`` records one sighting and returns how many times
    ``fp`` now appears in the window INCLUDING this sighting — so the
    first-ever sighting returns 1 (cold), the second returns 2, etc.
    """

    def __init__(self, size: int = 512):
        self._lock = threading.Lock()
        self._size = max(int(size), 1)
        self._order: "deque[object]" = deque()
        self._counts: "Counter[object]" = Counter()

    def observe(self, fingerprint: object, size: Optional[int] = None) -> int:
        with self._lock:
            if size is not None and int(size) >= 1:
                self._size = int(size)
            self._order.append(fingerprint)
            self._counts[fingerprint] += 1
            while len(self._order) > self._size:
                old = self._order.popleft()
                self._counts[old] -= 1
                if self._counts[old] <= 0:
                    del self._counts[old]
            return self._counts[fingerprint]

    def repeats(self, fingerprint: object) -> int:
        with self._lock:
            return self._counts.get(fingerprint, 0)

    def reset(self) -> None:
        with self._lock:
            self._order.clear()
            self._counts.clear()


def should_admit(
    nbytes: int,
    cost_s: float,
    repeats: int,
    byte_rate: int,
    max_bytes: int,
) -> str:
    """Classify one admission decision.

    Returns ``"admit"``, ``"declined_cold"`` (first sighting in the
    window), or ``"declined_bytes"`` (over the per-entry ceiling, or the
    cost×repeat-rate value does not cover the byte cost).
    """
    if nbytes > max_bytes:
        return "declined_bytes"
    if repeats < 2:
        return "declined_cold"
    if float(cost_s) * repeats * max(int(byte_rate), 1) < nbytes:
        return "declined_bytes"
    return "admit"


def recompute_cost_s(trace, fallback_s: float) -> float:
    """Observed recompute cost of one query: the summed wall of its
    device/interpreter execution spans (``compile.pipeline_run`` wraps
    dispatch + D2H; ``query.interpret`` is the fallback leg). Children
    like ``scan.device_dispatch`` nest INSIDE these, so summing only the
    top execution spans never double-counts. When tracing is off (the
    spans are conf-gated) the caller's direct wall measurement wins."""
    if trace is None:
        return max(float(fallback_s), 0.0)
    total = 0.0
    try:
        for s in trace.root.walk():
            if s.name in ("compile.pipeline_run", "query.interpret"):
                d = s.duration_s
                if d is not None:
                    total += d
    except Exception:  # noqa: BLE001 - a malformed trace must not fail a store
        from ..telemetry.metrics import metrics

        metrics.incr("serve.cache_policy.trace_error")
        return max(float(fallback_s), 0.0)
    return total if total > 0.0 else max(float(fallback_s), 0.0)
