"""Oversubscribed residency: the tier ladder that keeps tables larger
than the HBM budget on the device fast path.

PR 5's join regions and the base resident caches share one failure mode
at scale: once a table's raw int32 planes exceed the HBM budget, the
caches refuse it outright and every query pays the host path — the
admit/deny cliff BENCH_SCALE_SF100 measured (join speedups collapsing to
~1.1-1.3x at 600 M rows). PystachIO and Theseus (PAPERS.md) both reach
the same conclusion: storage->device movement must be a first-class
pipeline, not a boolean. This package supplies the ladder

    resident -> compressed -> streaming -> host

with two compounding levers:

* ``tiers``     — the ONE tier-planning procedure both caches call: given
  raw plane bytes, per-column pack plans and the budget, pick the
  cheapest tier that fits (and explain refusals).
* ``streaming`` — the block-window tier: pinned-host packed planes staged
  through a fixed pair of HBM slabs, upload of window k+1 overlapped
  with the mask of window k, per-window count partials the only D2H.
* ``knobs``     — the ``hyperspace.residency.*`` config family (constants
  registry, HS013) with HYPERSPACE_TPU_RESIDENCY_* env overrides.

Compression/decode codecs live in ``ops.bitpack`` (device code is ops/
territory); the caches integrate the ladder in exec/hbm_cache and
exec/mesh_cache (the mesh supports resident + compressed; streaming is
single-chip — a mesh table that large should shard wider instead, and
the decline is counted).
"""

from .knobs import (  # noqa: F401
    adopt_conf,
    compression_mode,
    for_delta_enabled,
    streaming_enabled,
    streaming_window_rows,
)
from .tiers import TierPlan, plan_tier  # noqa: F401
