"""The streaming block-window tier: device-speed scans over tables whose
(even compressed) predicate planes exceed the HBM budget.

The table's planes live PINNED ON HOST — packed words where the codec
wins (ops.bitpack), raw int32 where it doesn't — pre-sliced into
fixed-size windows. A scan stages windows through a fixed PAIR of HBM
slab slots: while the mask+count executable runs over window k, window
k+1's bytes ride the link into the other slot, so the link and the
compute overlap instead of serializing (the double-buffered H2D ingest
of the PR-6 build pipeline, applied to the query path; Theseus's
storage->device pipeline is the design exemplar). Per-window the device
keeps only the (mask -> per-8192-row-block count) partials; the ONLY
D2H is the per-window count vector — finished results, never operands.

Window geometry: ``window_rows`` (hyperspace.residency.streaming.
windowRows) padded up to a multiple of BLOCK_ROWS, which is itself a
multiple of the mask tile and of every pack word width (vpw is a power
of two <= 32), so window slices land on word boundaries and block
boundaries simultaneously. Pad rows can only add false-positive counts
in tail blocks — the host leg re-evaluates candidate blocks exactly, the
same clipping contract as the resident tiers.

Batching: streaming scans coalesce in the serve micro-batcher like any
resident scan, but only within a WINDOW GENERATION — ``window_gen``
bumps when a device failure tears the slab pair down, so a batch never
spans the discontinuity (serve/batcher folds it into the batch key).

This module is deliberately OUTSIDE exec/ (the HS001 boundary): it is
the one place streaming readbacks and fences live, exactly like the
cache modules are for the resident tiers.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..exec.bytecache import vocab_heap_bytes
from ..ops.bitpack import PackSpec, pack_plain
from ..telemetry.metrics import metrics
from ..telemetry.trace import add_bytes as _trace_bytes

# window padding grain: BLOCK_ROWS (8192) is a multiple of the mask tile
# (1024) and of every straddle-free word width, so one grain serves the
# count reduction, the tile and the packer simultaneously
_WINDOW_GRAIN = 8192

# an upload that completes under this is a prefetch HIT: the H2D landed
# while the previous window's kernel ran (the overlap working); above it
# the pipeline stalled on the link
_STALL_EPSILON_S = 0.002


@dataclass
class StreamPlane:
    """One host-pinned plane of a streaming column: packed words + spec,
    or a raw int32 flat (spec None). Length is padded to the table's
    window multiple so every window slice is full-size."""

    data: np.ndarray  # int32; words when spec is not None
    spec: Optional[PackSpec] = None


@dataclass
class StreamColumn:
    """Host-side column state; duck-typed against ResidentColumn for
    prepare_resident_predicate (enc / dtype_str / vocab)."""

    dtype_str: str
    enc: str  # 'int' | 'float32' | 'string' | 'f64'
    planes: Dict[str, StreamPlane]  # '' single-plane; 'hi'/'lo' for f64
    nbytes: int  # host bytes (pinned planes + vocab heap)
    vocab: Optional[np.ndarray] = None
    # int-encoded columns: value-space bounds over the real rows (the
    # scan-aggregate planner's input on tables without zone vectors;
    # streaming itself declines aggregation, so these are informational)
    vmin: Optional[int] = None
    vmax: Optional[int] = None


@dataclass
class StreamingResidentTable:
    """A resident-table stand-in at the streaming tier: same identity,
    coverage and zone surface as ResidentTable (the registry, lookup and
    selectivity-gate code paths serve it unchanged), but its planes are
    host-pinned and its budget charge is the SLAB PAIR, not the table."""

    tier = "streaming"

    key: tuple
    files: List[Tuple[str, int, int]]
    n_rows: int
    n_pad: int  # window-multiple padded rows
    window_rows: int
    n_windows: int
    columns: Dict[str, StreamColumn]
    nbytes: int  # budget-charged: 2 windows of operand bytes + vocab
    host_bytes: int  # pinned host planes (reported, not budget-charged)
    raw_nbytes: int  # what the planes would cost raw-resident (obsv.)
    zones: Dict[str, Tuple[str, np.ndarray, np.ndarray]] = field(
        default_factory=dict
    )
    window_gen: int = 0
    last_used: float = field(default_factory=time.monotonic)
    # serializes the window loop: the budget charges exactly ONE slab
    # pair per table, so concurrent scans must take turns — N parallel
    # loops would stage N pairs and blow the oversubscribed margin the
    # tier exists to respect (serve-side, compatible queries coalesce
    # into one loop anyway; only incompatible shapes ever queue here)
    _stream_lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False
    )

    def file_span(self, path: str) -> Optional[Tuple[int, int]]:
        for p, start, n in self.files:
            if p == path:
                return start, start + n
        return None


def window_pad_rows(window_rows: int) -> int:
    return -(-max(int(window_rows), 1) // _WINDOW_GRAIN) * _WINDOW_GRAIN


def build_streaming_table(
    key: tuple,
    spans: List[Tuple[str, int, int]],
    n_rows: int,
    host_planes: dict,
    zones: dict,
    specs: Dict[str, PackSpec],
    window_rows: int,
) -> StreamingResidentTable:
    """Assemble the streaming table from the cache build's host flats.

    ``host_planes`` maps column name -> (dtype_str, enc, vocab, planes)
    where planes maps plane key ('' or 'hi'/'lo') to an int32 flat of
    n_rows values; ``specs`` carries the adopted PackSpec per single-
    plane column (from the tier planner). Packing and window padding
    happen here — the one place the host layout is defined."""
    W = window_pad_rows(window_rows)
    n_pad = -(-n_rows // W) * W
    n_windows = n_pad // W
    columns: Dict[str, StreamColumn] = {}
    host_bytes = 0
    raw_bytes = 0
    window_operand_bytes = 0
    for name, (dtype_str, enc, vocab, planes) in host_planes.items():
        sp: Dict[str, StreamPlane] = {}
        vocab_heap = vocab_heap_bytes(vocab)
        col_bytes = vocab_heap
        for pkey, flat in planes.items():
            raw_bytes += n_pad * 4
            spec = specs.get(name) if pkey == "" else None
            if spec is not None:
                # re-spec over the padded length; pad rows decode to
                # ref0 (in-range garbage the host leg clips)
                spec = dataclasses.replace(spec, n=n_pad)
                padded = np.full(n_pad, spec.ref0, dtype=np.int64)
                padded[:n_rows] = flat[:n_rows]
                words = pack_plain(padded, spec)
                sp[pkey] = StreamPlane(words, spec)
                col_bytes += words.nbytes
                window_operand_bytes += 4 * (W // spec.vpw)
            else:
                padded32 = np.zeros(n_pad, dtype=np.int32)
                padded32[:n_rows] = flat[:n_rows]
                sp[pkey] = StreamPlane(padded32, None)
                col_bytes += padded32.nbytes
                window_operand_bytes += 4 * W
        columns[name] = StreamColumn(dtype_str, enc, sp, col_bytes, vocab)
        host_bytes += col_bytes
    return StreamingResidentTable(
        key,
        spans,
        n_rows,
        n_pad,
        W,
        n_windows,
        columns,
        2 * window_operand_bytes
        + sum(vocab_heap_bytes(c.vocab) for c in columns.values()),
        host_bytes,
        raw_bytes,
        zones,
    )


def _resolve_plane(table: StreamingResidentTable, name: str) -> StreamPlane:
    if "\x00" in name:
        base, pkey = name.split("\x00", 1)
        return table.columns[base].planes[pkey]
    return table.columns[name].planes[""]


def _window_slice(
    plane: StreamPlane, w: int, W: int
) -> Tuple[np.ndarray, Optional[PackSpec]]:
    if plane.spec is None:
        return plane.data[w * W : (w + 1) * W], None
    wspec = dataclasses.replace(plane.spec, n=W)
    vpw = plane.spec.vpw
    return plane.data[w * W // vpw : (w + 1) * W // vpw], wspec


def _upload_window(table, names, w):
    """device_put one window's operand slices — the H2D leg the loop
    overlaps with the previous window's kernel. Returns (cols dict,
    specs tuple aligned with ``names``, bytes)."""
    import jax

    W = table.window_rows
    cols = {}
    specs = []
    nbytes = 0
    for n in names:
        sl, wspec = _window_slice(_resolve_plane(table, n), w, W)
        cols[n] = jax.device_put(sl)
        specs.append(wspec)
        nbytes += int(sl.nbytes)
    _trace_bytes("h2d_bytes", nbytes)  # label at the transfer site
    return cols, tuple(specs), nbytes


def _windowed_counts(table, dispatch, union_names):
    """The double-buffered window loop shared by the single and batched
    entry points. ``dispatch(cols, specs)`` enqueues the window's jitted
    mask+count and returns the un-fetched device result; this loop owns
    the overlap, the prefetch-hit/stall accounting and the generation
    bump on device failure. Returns the per-window numpy results in
    window order."""
    return _run_window_loop(
        table, lambda w: _upload_window(table, union_names, w), dispatch
    )


def _run_window_loop(table, upload, dispatch):
    """The tier's one pipeline loop, shared by the single-chip and mesh
    tables: ``upload(w)`` stages window ``w``'s operand slices into the
    free slab slot (single-chip: one HBM pair; mesh: one pair PER
    DEVICE, the upload device_put'ing (D, window) slices under the shard
    sharding) and returns (cols, specs, bytes)."""
    import jax

    out: list = []
    slots: list = [None, None]
    with table._stream_lock:
        return _windowed_counts_locked(
            table, upload, dispatch, jax, out, slots
        )


def _windowed_counts_locked(table, upload, dispatch, jax, out, slots):
    try:
        t0 = time.perf_counter()
        slots[0] = upload(0)
        metrics.record_time(
            "residency.stream.h2d", time.perf_counter() - t0
        )
        for w in range(table.n_windows):
            cols, specs, up_bytes = slots[w % 2]
            metrics.incr("residency.stream.h2d_bytes", up_bytes)
            # the slot's upload was dispatched while the PREVIOUS window
            # computed; if it is already on device this wait is ~zero
            # (prefetch hit), else the pipeline stalled on the link
            t0 = time.perf_counter()
            jax.block_until_ready(list(cols.values()))
            stall = time.perf_counter() - t0
            if w > 0:
                if stall < _STALL_EPSILON_S:
                    metrics.incr("residency.stream.prefetch_hit")
                else:
                    metrics.incr("residency.stream.prefetch_stall")
                    metrics.record_time("residency.stream.stall", stall)
            pending = dispatch(cols, specs)  # enqueue compute, no fetch
            if w + 1 < table.n_windows:
                t0 = time.perf_counter()
                slots[(w + 1) % 2] = upload(w + 1)
                metrics.record_time(
                    "residency.stream.h2d", time.perf_counter() - t0
                )
            out.append(np.asarray(pending))  # D2H: count partials only
            metrics.incr("residency.stream.windows")
    except Exception:
        # a dead device mid-window tears the slab pair down: bump the
        # generation so in-flight serve batches never span the
        # discontinuity, then let the caller drop the table and latch
        # the query host-side (the resident tiers' exact contract)
        table.window_gen += 1
        metrics.incr("residency.stream.window_failed")
        raise
    return out


def stream_block_counts(table: StreamingResidentTable, predicate):
    """Per-BLOCK_ROWS match counts over the whole streamed table — the
    streaming twin of HbmIndexCache.block_counts. None when the
    predicate cannot ride the resident encodings (caller routes host);
    device errors propagate (caller drops + degrades)."""
    from ..exec.hbm_cache import (
        BLOCK_ROWS,
        _LANES,
        _counts_fn,
        prepare_resident_predicate,
    )
    from ..ops import kernels as K

    prepared = prepare_resident_predicate(table.columns, predicate)
    if prepared is None:
        return None
    narrowed, names = prepared
    t0 = time.perf_counter()

    def dispatch(cols, specs):
        fn = _counts_fn(
            narrowed, names, table.window_rows // _LANES, False, specs
        )
        with K._x32():
            return fn([cols[n] for n in names])

    parts = _windowed_counts(table, dispatch, names)
    metrics.record_time("scan.resident.device", time.perf_counter() - t0)
    counts = np.concatenate(parts)
    metrics.incr("scan.resident.d2h_bytes", int(counts.nbytes))
    _trace_bytes("d2h_bytes", int(counts.nbytes))
    n_blocks = -(-table.n_rows // BLOCK_ROWS)
    return counts[:n_blocks]


def stream_block_counts_batch(
    table: StreamingResidentTable, predicates, prepared=None
):
    """(N, n_blocks) counts for N compatible predicates, every window
    dispatched ONCE for the whole batch — the streaming leg of the serve
    micro-batcher. None when any predicate fails to narrow."""
    from ..exec.hbm_cache import (
        BLOCK_ROWS,
        _LANES,
        _batched_counts_fn,
        _expr_literals,
        _expr_structure,
        prepare_resident_predicate,
    )
    from ..ops import kernels as K

    if prepared is None:
        prepared = [
            prepare_resident_predicate(table.columns, p) for p in predicates
        ]
    if any(p is None for p in prepared):
        return None
    structures = tuple(_expr_structure(n) for n, _ in prepared)
    slot_names = tuple(names for _, names in prepared)
    exprs = [n for n, _ in prepared]
    union_names = tuple(
        dict.fromkeys(n for names in slot_names for n in names)
    )
    lit_vecs = []
    for narrowed, _ in prepared:
        vals: list = []
        _expr_literals(narrowed, vals)
        lit_vecs.append(np.asarray(vals, dtype=np.int32))
    lit_vecs = tuple(lit_vecs)
    t0 = time.perf_counter()

    def dispatch(cols, specs):
        spec_map = tuple(zip(union_names, specs))
        fn = _batched_counts_fn(
            structures,
            slot_names,
            exprs,
            table.window_rows // _LANES,
            spec_map,
        )
        with K._x32():
            return fn(cols, lit_vecs)

    parts = _windowed_counts(table, dispatch, union_names)
    metrics.record_time("serve.batch.device", time.perf_counter() - t0)
    metrics.incr("serve.batch.dispatches")
    metrics.incr("serve.batch.queries", len(predicates))
    counts = np.concatenate(parts, axis=1)
    metrics.incr("scan.resident.d2h_bytes", int(counts.nbytes))
    _trace_bytes("d2h_bytes", int(counts.nbytes))
    n_blocks = -(-table.n_rows // BLOCK_ROWS)
    return counts[:, :n_blocks]


# ---------------------------------------------------------------------------
# the MESH streaming rung: host-pinned shard matrices, a slab pair per
# device — the compressed-streaming tier the mesh ladder declined until
# now. Window w stages the (D, W) column slices under the mesh sharding
# (one device_put lands every shard's slab), the shard_map mask+count
# runs per device, and only (D, W // block) count partials come home.
# The budget charge is the PER-DEVICE slab pair times D — two windows of
# operand bytes across the mesh, regardless of table size.
# ---------------------------------------------------------------------------


@dataclass
class MeshStreamingResidentTable:
    """A MeshResidentTable stand-in at the streaming tier: same
    identity, coverage, segments and block geometry (collect_parts and
    the registry serve it unchanged), but its planes are host-pinned
    (D, padded-cap) matrices and the budget charge is the slab pair."""

    tier = "streaming"

    key: tuple
    mesh: object
    n_devices: int
    cap: int  # per-device rows padded to the window multiple
    block: int
    dev_rows: List[int]
    segments: List[List]  # per device, dev_off-ascending (mesh_cache)
    columns: Dict[str, StreamColumn]  # planes hold (D, ...) matrices
    n_rows: int
    n_pad: int  # == n_devices * cap (total padded rows)
    window_rows: int
    n_windows: int
    nbytes: int  # budget-charged: 2 windows of operand bytes (all shards)
    host_bytes: int
    raw_nbytes: int
    window_gen: int = 0
    last_used: float = field(default_factory=time.monotonic)
    _stream_lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False
    )

    @property
    def n_blocks(self) -> int:
        return self.cap // self.block


def build_mesh_streaming_table(
    key: tuple,
    mesh,
    dev_segs,
    dev_rows,
    n_rows: int,
    host_mats: dict,
    specs: Dict[str, PackSpec],
    window_rows: int,
    col_bounds: Optional[dict] = None,
) -> MeshStreamingResidentTable:
    """Assemble the mesh streaming table from the mesh build's host
    (D, cap) matrices. ``host_mats`` maps column name -> (dtype_str,
    enc, vocab, {plane_key: (D, cap) int32 matrix}); ``specs`` carries
    the adopted PackSpec per packable column (global frame, one static
    spec serves every shard — the mesh compressed rule)."""
    D = int(mesh.devices.size)
    some = next(iter(host_mats.values()))
    cap_in = next(iter(some[3].values())).shape[1]
    W = window_pad_rows(window_rows)
    cap = -(-cap_in // W) * W
    n_windows = cap // W
    columns: Dict[str, StreamColumn] = {}
    host_bytes = 0
    raw_bytes = 0
    window_operand_bytes = 0
    for name, (dtype_str, enc, vocab, planes) in host_mats.items():
        sp: Dict[str, StreamPlane] = {}
        vocab_heap = vocab_heap_bytes(vocab)
        col_bytes = vocab_heap
        for pkey, mat in planes.items():
            raw_bytes += D * cap * 4
            spec = specs.get(name) if pkey == "" else None
            if spec is not None:
                # pad rows re-encode at the frame reference (zero pads
                # may sit outside the frame for offset domains — the
                # mesh compressed rule); ref0 pads are in-range garbage
                # the host leg clips
                wspec = dataclasses.replace(spec, n=cap)
                padded = np.full((D, cap), wspec.ref0, dtype=np.int64)
                for d in range(D):
                    padded[d, : dev_rows[d]] = mat[d, : dev_rows[d]]
                words = np.stack(
                    [pack_plain(padded[d], wspec) for d in range(D)]
                )
                sp[pkey] = StreamPlane(words, wspec)
                col_bytes += words.nbytes
                window_operand_bytes += 4 * D * (W // wspec.vpw)
            else:
                padded32 = np.zeros((D, cap), dtype=np.int32)
                padded32[:, :cap_in] = mat
                sp[pkey] = StreamPlane(padded32, None)
                col_bytes += padded32.nbytes
                window_operand_bytes += 4 * D * W
        bounds = (col_bounds or {}).get(name, (None, None))
        columns[name] = StreamColumn(
            dtype_str, enc, sp, col_bytes, vocab, bounds[0], bounds[1]
        )
        host_bytes += col_bytes
    return MeshStreamingResidentTable(
        key,
        mesh,
        D,
        cap,
        min(8192, cap),
        list(dev_rows),
        dev_segs,
        columns,
        n_rows,
        D * cap,
        W,
        n_windows,
        2 * window_operand_bytes
        + sum(vocab_heap_bytes(c.vocab) for c in columns.values()),
        host_bytes,
        raw_bytes,
    )


def _mesh_upload_window(table: MeshStreamingResidentTable, names, w: int):
    """device_put one window's (D, slice) operand matrices under the
    mesh sharding — ONE put per column lands every shard's slab slot."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(
        table.mesh, PartitionSpec(table.mesh.axis_names[0], None)
    )
    W = table.window_rows
    cols = {}
    specs = []
    nbytes = 0
    for n in names:
        plane = _resolve_plane(table, n)
        if plane.spec is None:
            sl = plane.data[:, w * W : (w + 1) * W]
            wspec = None
        else:
            vpw = plane.spec.vpw
            sl = plane.data[:, w * W // vpw : (w + 1) * W // vpw]
            wspec = dataclasses.replace(plane.spec, n=W)
        cols[n] = jax.device_put(np.ascontiguousarray(sl), sharding)
        specs.append(wspec)
        nbytes += int(sl.nbytes)
    _trace_bytes("h2d_bytes", nbytes)  # label at the transfer site
    return cols, tuple(specs), nbytes


def mesh_stream_block_counts(table: MeshStreamingResidentTable, predicate):
    """(D, n_blocks) match counts over the streamed mesh shards — the
    streaming twin of MeshHbmCache.block_counts. None when the
    predicate cannot ride the resident encodings; device errors
    propagate (caller drops + degrades)."""
    from ..exec.hbm_cache import prepare_resident_predicate
    from ..exec.mesh_cache import _mesh_counts_fn
    from ..ops import kernels as K

    prepared = prepare_resident_predicate(table.columns, predicate)
    if prepared is None:
        return None
    narrowed, names = prepared
    t0 = time.perf_counter()

    def dispatch(cols, specs):
        fn = _mesh_counts_fn(
            table.mesh,
            repr(narrowed),
            narrowed,
            names,
            table.window_rows,
            table.block,
            specs,
        )
        with K._x32():
            return fn(cols)

    parts = _run_window_loop(
        table, lambda w: _mesh_upload_window(table, names, w), dispatch
    )
    metrics.record_time(
        "scan.resident_mesh.device", time.perf_counter() - t0
    )
    counts = np.concatenate(parts, axis=1)
    metrics.incr("scan.resident_mesh.d2h_bytes", int(counts.nbytes))
    _trace_bytes("d2h_bytes", int(counts.nbytes))
    return counts


def mesh_stream_block_counts_batch(
    table: MeshStreamingResidentTable,
    predicates,
    prepared=None,
    metric_ns: str = "serve.batch",
):
    """Per-predicate (D, n_blocks) counts for N compatible predicates,
    every window dispatched ONCE for the whole batch — the mesh
    streaming leg of the serve micro-batcher and (N=1) the compiled
    mesh scan pipeline. None when any predicate fails to narrow."""
    from ..exec.hbm_cache import (
        _expr_literals,
        _expr_structure,
        prepare_resident_predicate,
    )
    from ..exec.mesh_cache import _mesh_batched_counts_fn
    from ..ops import kernels as K

    if prepared is None:
        prepared = [
            prepare_resident_predicate(table.columns, p) for p in predicates
        ]
    if any(p is None for p in prepared):
        return None
    structures = tuple(_expr_structure(n) for n, _ in prepared)
    slot_names = tuple(names for _, names in prepared)
    exprs = [n for n, _ in prepared]
    union_names = tuple(
        dict.fromkeys(n for names in slot_names for n in names)
    )
    lit_vecs = []
    for narrowed, _ in prepared:
        vals: list = []
        _expr_literals(narrowed, vals)
        lit_vecs.append(np.asarray(vals, dtype=np.int32))
    lit_vecs = tuple(lit_vecs)
    t0 = time.perf_counter()

    def dispatch(cols, specs):
        spec_map = tuple(zip(union_names, specs))
        fn = _mesh_batched_counts_fn(
            table.mesh,
            structures,
            slot_names,
            exprs,
            table.window_rows,
            table.block,
            spec_map,
        )
        with K._x32():
            return fn(cols, lit_vecs)

    parts = _run_window_loop(
        table,
        lambda w: _mesh_upload_window(table, union_names, w),
        dispatch,
    )
    metrics.record_time(f"{metric_ns}.mesh_device", time.perf_counter() - t0)
    metrics.incr(f"{metric_ns}.dispatches")
    metrics.incr(f"{metric_ns}.queries", len(predicates))
    # per-window (D, N, W // block) -> (D, N, blocks) -> predicate-major
    counts = np.concatenate(parts, axis=2)
    metrics.incr("scan.resident_mesh.d2h_bytes", int(counts.nbytes))
    _trace_bytes("d2h_bytes", int(counts.nbytes))
    return np.swapaxes(counts, 0, 1)
