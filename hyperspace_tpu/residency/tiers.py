"""The ONE tier-planning procedure of the residency ladder.

Both caches (exec/hbm_cache, exec/mesh_cache) size a candidate table
here instead of comparing raw bytes to the budget inline — the rule that
turned the budget from an admission wall into a ladder must have exactly
one copy, or the two caches (and the bench's A/B legs) drift.

The ladder, cheapest-at-query-time first:

  resident    raw int32 planes fit the budget — the PR-3/PR-5 behavior.
  compressed  bit-packed planes (ops.bitpack) fit where raw did not;
              budget accounting charges COMPRESSED bytes, multiplying
              effective capacity by the pack ratio.
  streaming   even packed planes exceed headroom: host-pinned planes
              staged through a fixed pair of HBM slabs, so the budget
              charge is two windows regardless of table size.
  host        streaming disabled or the slab pair itself cannot fit.

Compression mode "force" skips the resident rung for packable columns
(capacity-over-latency deployments, and the tests' way of exercising
the codec without multi-GB fixtures).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Optional

from ..ops.bitpack import PackSpec
from ..telemetry.metrics import metrics
from . import knobs

# --- budget claimants ---------------------------------------------------------
# Non-residency holders of budget-charged bytes (today: the result
# caches). A claimant exposes ``held_bytes() -> int`` and
# ``shed(nbytes) -> int`` (bytes actually freed). Claimant bytes charge
# against the SAME env HBM budget the caches divide, and the eviction
# ladder sheds them FIRST — cached results are cheaper to drop than any
# resident delta or table (recompute is one query; re-residency is a
# rebuild + upload).

_CLAIMANTS_LOCK = threading.Lock()
_CLAIMANTS: Dict[str, object] = {}


def register_claimant(name: str, claimant: object) -> None:
    with _CLAIMANTS_LOCK:
        _CLAIMANTS[name] = claimant


def claimant_bytes() -> int:
    """Total budget-charged bytes held by registered claimants."""
    with _CLAIMANTS_LOCK:
        holders = list(_CLAIMANTS.values())
    total = 0
    for c in holders:
        try:
            total += int(c.held_bytes())
        except Exception:  # noqa: BLE001 - one claimant must not wedge budget math
            metrics.incr("residency.claimant.error")
            continue
    return total


def shed_claimants(nbytes: int) -> int:
    """Free at least ``nbytes`` of claimant-held budget, cheapest rung
    first. Returns bytes actually freed (may fall short — the residency
    caches then continue down their own ladder: deltas, joins, tables)."""
    if nbytes <= 0:
        return 0
    with _CLAIMANTS_LOCK:
        holders = list(_CLAIMANTS.values())
    freed = 0
    for c in holders:
        if freed >= nbytes:
            break
        try:
            freed += int(c.shed(nbytes - freed))
        except Exception:  # noqa: BLE001 - one claimant must not wedge eviction
            metrics.incr("residency.claimant.error")
            continue
    return freed


@dataclass
class TierPlan:
    """Outcome of plan_tier. ``specs`` maps column name -> PackSpec for
    every column the chosen tier packs (empty for tier "resident");
    ``window_rows`` is set for tier "streaming" (pre-tile-padding)."""

    tier: str  # "resident" | "compressed" | "streaming" | "host"
    reason: str = ""
    specs: Dict[str, PackSpec] = field(default_factory=dict)
    window_rows: int = 0
    raw_bytes: int = 0
    packed_bytes: int = 0


def plan_tier(
    raw_plane_bytes: int,
    budget_bytes: int,
    pack_specs: Optional[Dict[str, PackSpec]] = None,
    unpacked_plane_bytes: int = 0,
    side_bytes: int = 0,
    streaming_ok: bool = True,
    shard_count: int = 1,
) -> TierPlan:
    """Pick the cheapest tier that fits ``budget_bytes``.

    ``raw_plane_bytes``  — device bytes of every plane stored raw;
    ``pack_specs``       — per-column PackSpec for the packable columns
                           (None/empty = nothing packs);
    ``unpacked_plane_bytes`` — device bytes of the planes that stay raw
                           even under compression (unpackable columns);
    ``side_bytes``       — budget-charged non-plane bytes (host vocab
                           heaps) that ride along at every tier;
    ``streaming_ok``     — caller-side eligibility (the mesh cache and
                           delta/join regions pass False: streaming is a
                           base-table, single-chip tier);
    ``shard_count``      — device shards each pack spec materializes on
                           (the mesh passes D: its per-shard specs cost
                           D copies, and the fit check must price what
                           the build will actually upload).
    """
    mode = knobs.compression_mode()
    specs = dict(pack_specs or {})
    packed_bytes = (
        sum(s.packed_nbytes for s in specs.values()) * max(shard_count, 1)
        + unpacked_plane_bytes
    )
    force = mode == "force" and specs
    if raw_plane_bytes + side_bytes <= budget_bytes and not force:
        return TierPlan(
            "resident", "raw fits", {}, 0, raw_plane_bytes, packed_bytes
        )
    if mode != "off" and specs and packed_bytes + side_bytes <= budget_bytes:
        return TierPlan(
            "compressed",
            "packed fits" if not force else "compression forced",
            specs,
            0,
            raw_plane_bytes,
            packed_bytes,
        )
    if force:
        # forced but over budget: fall through the remaining rungs with
        # the packed planes still in play (streaming streams packed)
        pass
    if streaming_ok and knobs.streaming_enabled():
        return TierPlan(
            "streaming",
            "oversubscribed",
            specs if mode != "off" else {},
            knobs.streaming_window_rows(),
            raw_plane_bytes,
            packed_bytes,
        )
    return TierPlan(
        "host",
        "streaming disabled" if streaming_ok else "tier ineligible",
        {},
        0,
        raw_plane_bytes,
        packed_bytes,
    )
