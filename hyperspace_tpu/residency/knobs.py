"""The ``hyperspace.residency.*`` knob family.

The resident caches are process-global singletons while conf is
per-session, so wiring follows the precedent of the HYPERSPACE_TPU_HBM
family: env vars are authoritative (operators, tests), and the session
pushes its conf values here as process DEFAULTS at construction
(``HyperspaceSession.__init__`` -> ``adopt_conf``) — the last session's
conf wins, which matches how the one shared budget already behaves.
Every dotted key is declared in constants.py (the HS013 registry);
malformed env values fall back to the default, never raise (the
bytecache env_* discipline).
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from .. import constants as C

_lock = threading.Lock()
_conf_defaults: dict = {}


def adopt_conf(conf) -> None:
    """Adopt a session conf's residency knobs as process defaults.
    Absent keys leave the constants defaults in place. Values are read
    THROUGH the typed config accessors so an invalid value raises
    HyperspaceException at session construction — the value-typo twin of
    HS013's key-typo failure mode must not be silently ignored here."""
    vals = {}
    if conf.contains(C.RESIDENCY_COMPRESSION):
        vals[C.RESIDENCY_COMPRESSION] = conf.residency_compression()
    if conf.contains(C.RESIDENCY_STREAMING):
        vals[C.RESIDENCY_STREAMING] = conf.residency_streaming()
    if conf.contains(C.RESIDENCY_STREAMING_WINDOW_ROWS):
        vals[C.RESIDENCY_STREAMING_WINDOW_ROWS] = conf.residency_window_rows()
    if conf.contains(C.RESIDENCY_FOR_DELTA):
        vals[C.RESIDENCY_FOR_DELTA] = conf.residency_for_delta()
    with _lock:
        _conf_defaults.update(vals)


def _value(env: str, key: str, default) -> object:
    v = os.environ.get(env)
    if v is not None and v != "":
        return v
    with _lock:
        return _conf_defaults.get(key, default)


def compression_mode() -> str:
    v = str(
        _value(
            "HYPERSPACE_TPU_RESIDENCY_COMPRESSION",
            C.RESIDENCY_COMPRESSION,
            C.RESIDENCY_COMPRESSION_DEFAULT,
        )
    ).lower()
    return (
        v
        if v in C.RESIDENCY_COMPRESSION_MODES
        else C.RESIDENCY_COMPRESSION_DEFAULT
    )


def streaming_enabled() -> bool:
    v = str(
        _value(
            "HYPERSPACE_TPU_RESIDENCY_STREAMING",
            C.RESIDENCY_STREAMING,
            C.RESIDENCY_STREAMING_DEFAULT,
        )
    ).lower()
    # accept the common falsy spellings like for_delta_enabled does —
    # an operator's STREAMING=false must not silently mean "on"
    return v not in (C.RESIDENCY_STREAMING_OFF, "false", "0", "no")


def streaming_window_rows() -> int:
    raw = _value(
        "HYPERSPACE_TPU_RESIDENCY_WINDOW_ROWS",
        C.RESIDENCY_STREAMING_WINDOW_ROWS,
        C.RESIDENCY_STREAMING_WINDOW_ROWS_DEFAULT,
    )
    try:
        n = int(raw)
    except (TypeError, ValueError):
        return C.RESIDENCY_STREAMING_WINDOW_ROWS_DEFAULT
    return n if n > 0 else C.RESIDENCY_STREAMING_WINDOW_ROWS_DEFAULT


def for_delta_enabled() -> bool:
    v = str(
        _value(
            "HYPERSPACE_TPU_RESIDENCY_FOR_DELTA",
            C.RESIDENCY_FOR_DELTA,
            C.RESIDENCY_FOR_DELTA_DEFAULT,
        )
    ).lower()
    return v not in ("off", "false", "0", "no")


def reset_conf_defaults(values: Optional[dict] = None) -> None:
    """Test hook: clear (or replace) the adopted conf defaults."""
    with _lock:
        _conf_defaults.clear()
        if values:
            _conf_defaults.update(values)
