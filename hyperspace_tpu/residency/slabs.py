"""Build-side HBM slab accounting, shared with the residency budget.

The device-resident streaming build (docs/14-build-pipeline.md) pins
device memory OUTSIDE the residency caches: the double-buffered upload
slab pair and up to ``runChunks`` staged sorted chunks awaiting their
on-device run merge. Those bytes come out of the SAME physical HBM the
tier ladder budgets, so they must share the one budget instead of
silently oversubscribing it: a build that stages 3 GB of runs while the
caches believe they own the full 4 GB budget is exactly the blown
margin the ladder exists to prevent.

Discipline:

* a build RESERVES its worst-case slab footprint here before staging
  its first chunk (``try_reserve``) and releases it at finalize/abort —
  reservation is all-or-nothing, so a failed build can never leak a
  partial charge;
* reservations are capped at HALF the budget (``_BUILD_FRACTION``): the
  build may borrow headroom but never starve the serving caches — a
  build that needs more falls back to the per-chunk round-trip path
  (counted ``build.device.staging_declined.budget``), it does not queue;
* the caches see the borrowed bytes through ``held_bytes()``, which
  ``exec.hbm_cache._budget_bytes`` subtracts — their LRU eviction then
  makes room exactly as if a new table had been admitted.

This module deliberately holds NO jax arrays and NO references into the
build: it is pure byte bookkeeping, so the reservation lifetime is the
writer's explicit reserve/release calls and nothing can pin device
memory through it.
"""

from __future__ import annotations

import threading
from typing import Dict

from ..telemetry.metrics import metrics

# the build may reserve at most this fraction of the shared HBM budget;
# the rest always remains the serving caches' floor
_BUILD_FRACTION = 2  # denominator: budget // 2

_lock = threading.Lock()
_held: Dict[str, int] = {}


def _budget_total() -> int:
    from ..exec.bytecache import env_mb

    return env_mb("HYPERSPACE_TPU_HBM_BUDGET_MB", 4096)


def try_reserve(tag: str, nbytes: int) -> bool:
    """Reserve ``nbytes`` of build slab headroom under ``tag`` (one tag
    per writer; re-reserving a live tag replaces its charge). False =
    over the build's half-budget cap — the caller declines staging."""
    nbytes = max(0, int(nbytes))
    cap = _budget_total() // _BUILD_FRACTION
    with _lock:
        others = sum(v for k, v in _held.items() if k != tag)
        if others + nbytes > cap:
            metrics.incr("build.device.slab_reserve_refused")
            return False
        _held[tag] = nbytes
        total = others + nbytes
    metrics.gauge("build.device.slab_reserved_bytes", total)
    return True


def release(tag: str) -> None:
    """Drop ``tag``'s reservation. Idempotent — abort paths may race
    finalize teardown and both must be safe to call."""
    with _lock:
        _held.pop(tag, None)
        total = sum(_held.values())
    metrics.gauge("build.device.slab_reserved_bytes", total)


def held_bytes() -> int:
    """Bytes currently reserved by builds — what the residency caches
    subtract from their budget (exec.hbm_cache._budget_bytes)."""
    with _lock:
        return sum(_held.values())
