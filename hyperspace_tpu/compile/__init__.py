"""Whole-plan compilation (docs/17-plan-compilation.md): lower optimized
plan subtrees to ONE fused pipeline — the interpreter becomes the
fallback leg of a compiler. Public surface:

* ``pipeline_cache.get_or_lower(plan, executor, version_token)`` — the
  compiled-pipeline cache (exec.executor's entry point);
* ``result_cache`` — the RESULT memo stub riding the same tokens;
* ``plan_fingerprint`` / ``batch_fingerprint`` — the structural keys
  (the serve micro-batcher folds the coarse one into batch keys).
"""

from .cache import PipelineCache, pipeline_cache
from .fingerprint import batch_fingerprint, plan_fingerprint
from .lowering import classify_shape, lower
from .pipeline import CompiledPipeline
from .result_cache import ResultCache, result_cache

__all__ = [
    "CompiledPipeline",
    "PipelineCache",
    "ResultCache",
    "batch_fingerprint",
    "classify_shape",
    "lower",
    "pipeline_cache",
    "plan_fingerprint",
    "result_cache",
]
