"""RESULT cache stub riding the pipeline fingerprint machinery (the
ROADMAP PR-9 follow-up): memoize FINISHED result tables keyed on
(value-level plan signature, index-log version token).

Unlike the pipeline cache, results depend on literal VALUES — the key is
the serve plan cache's ``plan_signature`` (tree string with literals +
every leaf's file snapshot) plus the full version token, so a hit is
sound by construction: same literals, same source snapshot, same index
generation, same conf. Scoped invalidation rides the same version
tokens PR 9 pins — any create/refresh/optimize/delete changes the token
and old entries age out of the LRU; ``invalidate(index_root)`` drops a
rewritten index's entries eagerly (the collection-manager hook).

Off by default (``hyperspace.compile.resultCache``); bounded by entry
count AND a per-entry byte ceiling — this is a stub for point lookups
and small aggregates, not a materialized-view store. Served batches are
shared objects: ColumnarBatch is treated as immutable everywhere in the
executor (transforms build new batches), the same contract the serve
micro-batcher relies on.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Tuple

from ..telemetry.metrics import metrics


class ResultCache:
    """Bounded LRU: (plan signature, version token) -> (batch, roots)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._results: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._epoch = 0

    def get(self, key: tuple) -> Optional[object]:
        with self._lock:
            hit = self._results.get(key)
            if hit is not None:
                self._results.move_to_end(key)
        if hit is None:
            metrics.incr("compile.result_cache.miss")
            return None
        metrics.incr("compile.result_cache.hit")
        return hit[0]

    def put(
        self,
        key: tuple,
        batch,
        index_roots: Tuple[str, ...],
        max_entries: int,
        max_bytes: int,
    ) -> bool:
        """Memoize ``batch`` (False when it exceeds the byte ceiling)."""
        from ..exec.bytecache import batch_nbytes

        if batch_nbytes(batch) > max_bytes:
            metrics.incr("compile.result_cache.too_large")
            return False
        with self._lock:
            self._results[key] = (batch, tuple(index_roots))
            self._results.move_to_end(key)
            while len(self._results) > max(int(max_entries), 1):
                self._results.popitem(last=False)
                metrics.incr("compile.result_cache.evicted")
        metrics.incr("compile.result_cache.stored")
        return True

    def invalidate(self, index_root: Optional[str] = None) -> int:
        prefix = None
        if index_root is not None:
            prefix = str(index_root).rstrip("/") + "/"
        with self._lock:
            if prefix is None:
                n = len(self._results)
                self._results.clear()
            else:
                doomed = [
                    k
                    for k, (_b, roots) in self._results.items()
                    if any(p.startswith(prefix) for p in roots)
                ]
                for k in doomed:
                    del self._results[k]
                n = len(doomed)
        if n:
            metrics.incr("compile.result_cache.invalidated", n)
        return n

    def reset(self) -> None:
        with self._lock:
            self._results.clear()
            self._epoch += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {"entries": len(self._results)}


result_cache = ResultCache()


def result_key(
    plan, version_token: tuple, signature: Optional[tuple] = None
) -> tuple:
    """The ONE memo-key convention: the serve plan cache's value-level
    signature (literals + file snapshots) plus the full version token
    (index generation + conf). ``signature`` accepts a caller-
    precomputed ``plan_signature(plan)`` so the server path shares one
    tree walk with the plan cache."""
    if signature is None:
        from ..serve.plan_cache import plan_signature

        signature = plan_signature(plan)
    return (signature, version_token)


def result_roots(optimized_plan) -> Tuple[str, ...]:
    """Scoped-invalidation anchors of the OPTIMIZED plan (what actually
    served the result) — the fingerprint module's ONE anchor convention,
    shared with the pipeline cache."""
    from .fingerprint import index_roots

    return index_roots(optimized_plan)
