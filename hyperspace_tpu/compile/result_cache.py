"""RESULT cache riding the pipeline fingerprint machinery: memoize
FINISHED result tables keyed on (value-level plan signature, index-log
version token).

Unlike the pipeline cache, results depend on literal VALUES — the key is
the serve plan cache's ``plan_signature`` (tree string with literals +
every leaf's file snapshot) plus the full version token, so a hit is
sound by construction: same literals, same source snapshot, same index
generation, same conf. Scoped invalidation rides the same version
tokens PR 9 pins — any create/refresh/optimize/delete changes the token
and old entries age out; ``invalidate(index_root)`` drops a rewritten
index's entries eagerly (the collection-manager hook).

Two policies replaced the PR-10 LRU stub (docs/17):

* **Telemetry-driven admission** (serve/cache_policy): a result enters
  only when its observed recompute cost × its fingerprint's repeat rate
  beats its byte cost — callers pass both signals from the query's own
  trace; cold structures always decline.
* **GDSF eviction**: priority = clock + (1 + hits) × recompute_cost /
  bytes, with the classic aging clock (set to each victim's priority) so
  stale expensive entries cannot squat forever. Cheap-to-recompute bulky
  entries go first; hot expensive point lookups survive.

The cache's bytes charge against the SAME HBM budget ladder residency
uses: each instance registers as a ``residency.tiers`` claimant, and the
hbm-cache eviction ladder sheds claimant bytes BEFORE deltas — cached
results are the cheapest thing on the ladder to drop.

Pinned-token wholesale semantics: entries under an OLD version token are
never proactively dropped on token change — a snapshot-pinned reader
presenting its pinned token still hits them, and a reader on the new
token simply misses (counted ``stale_miss`` when the same signature
exists under another token). Served batches are shared objects:
ColumnarBatch is treated as immutable everywhere in the executor.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from ..telemetry.metrics import metrics


class ResultCache:
    """Cost-aware result memo: (plan signature, version token) ->
    finished batch, GDSF-evicted, byte-budgeted. ``prefix`` names the
    counter family — the serve-level instance reports under
    ``compile.result_cache.*``, the router-level one under
    ``router.result_cache.*``."""

    def __init__(self, prefix: str = "compile.result_cache"):
        self._prefix = prefix
        self._lock = threading.Lock()
        # key -> mutable entry dict {batch, roots, nbytes, hits, cost_s,
        # pri}; plain dict (insertion order only matters for tie-breaks)
        self._results: Dict[tuple, dict] = {}
        # signature -> set of full keys (stale_miss detection: same
        # signature alive under a DIFFERENT token)
        self._by_sig: Dict[object, set] = {}
        self._bytes = 0
        self._clock = 0.0
        self._epoch = 0

    # -- internals (call with lock held) -------------------------------------
    def _priority_locked(self, e: dict) -> float:
        return self._clock + (1 + e["hits"]) * e["cost_s"] / max(
            e["nbytes"], 1
        )

    def _drop_locked(self, key: tuple) -> dict:
        e = self._results.pop(key)
        self._bytes -= e["nbytes"]
        sigs = self._by_sig.get(key[0])
        if sigs is not None:
            sigs.discard(key)
            if not sigs:
                del self._by_sig[key[0]]
        return e

    def _evict_one_locked(self) -> bool:
        if not self._results:
            return False
        victim = min(self._results, key=lambda k: self._results[k]["pri"])
        self._clock = self._results[victim]["pri"]
        self._drop_locked(victim)
        return True

    # -- lookup ---------------------------------------------------------------
    def get(self, key: tuple) -> Optional[object]:
        stale = False
        with self._lock:
            e = self._results.get(key)
            if e is not None:
                e["hits"] += 1
                e["pri"] = self._priority_locked(e)
                batch = e["batch"]
            else:
                sigs = self._by_sig.get(key[0])
                stale = bool(sigs)
        if e is None:
            metrics.incr(self._prefix + ".miss")
            if stale:
                metrics.incr(self._prefix + ".stale_miss")
            return None
        metrics.incr(self._prefix + ".hit")
        return batch

    # -- admission ------------------------------------------------------------
    def put(
        self,
        key: tuple,
        batch,
        index_roots: Tuple[str, ...],
        max_entries: int,
        max_bytes: int,
        cost_s: float = 0.0,
        repeats: int = 0,
        byte_rate: int = 1,
        total_max_bytes: Optional[int] = None,
        nbytes: Optional[int] = None,
    ) -> str:
        """Admission decision for ``batch``: returns ``"admitted"``,
        ``"declined_cold"`` or ``"declined_bytes"``. ``cost_s`` is the
        observed recompute wall, ``repeats`` the fingerprint's sighting
        count in the admission window (cache_policy.AdmissionWindow),
        ``total_max_bytes`` the cache-wide budget share."""
        from ..serve.cache_policy import should_admit

        if nbytes is None:
            from ..exec.bytecache import batch_nbytes

            nbytes = batch_nbytes(batch)
        cap = total_max_bytes if total_max_bytes is not None else max_bytes
        verdict = should_admit(
            nbytes, cost_s, repeats, byte_rate, min(max_bytes, cap)
        )
        if verdict != "admit":
            metrics.incr(self._prefix + "." + verdict)
            return verdict
        with self._lock:
            old = self._results.get(key)
            if old is not None:
                self._drop_locked(key)
            e = {
                "batch": batch,
                "roots": tuple(index_roots),
                "nbytes": int(nbytes),
                "hits": 0 if old is None else old["hits"],
                "cost_s": max(float(cost_s), 0.0),
            }
            e["pri"] = self._priority_locked(e)
            self._results[key] = e
            self._by_sig.setdefault(key[0], set()).add(key)
            self._bytes += e["nbytes"]
            evicted = 0
            while len(self._results) > max(int(max_entries), 1) or (
                self._bytes > cap and len(self._results) > 1
            ):
                if not self._evict_one_locked():
                    break
                evicted += 1
        if evicted:
            metrics.incr(self._prefix + ".evicted", evicted)
        metrics.incr(self._prefix + ".admitted")
        return "admitted"

    # -- budget claimant protocol (residency.tiers) ---------------------------
    def held_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def shed(self, nbytes: int) -> int:
        """Free at least ``nbytes`` by GDSF eviction (the residency
        ladder's first rung: cached results drop BEFORE deltas). Returns
        bytes actually freed."""
        freed = 0
        evicted = 0
        with self._lock:
            while freed < nbytes and self._results:
                before = self._bytes
                if not self._evict_one_locked():
                    break
                freed += before - self._bytes
                evicted += 1
        if evicted:
            metrics.incr(self._prefix + ".evicted", evicted)
        return freed

    # -- invalidation ----------------------------------------------------------
    def invalidate(self, index_root: Optional[str] = None) -> int:
        prefix = None
        if index_root is not None:
            prefix = str(index_root).rstrip("/") + "/"
        with self._lock:
            if prefix is None:
                doomed = list(self._results)
            else:
                doomed = [
                    k
                    for k, e in self._results.items()
                    if any(p.startswith(prefix) for p in e["roots"])
                ]
            for k in doomed:
                self._drop_locked(k)
            n = len(doomed)
        if n:
            metrics.incr(self._prefix + ".invalidated", n)
        return n

    def reset(self) -> None:
        with self._lock:
            self._results.clear()
            self._by_sig.clear()
            self._bytes = 0
            self._clock = 0.0
            self._epoch += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._results),
                "bytes": self._bytes,
                "clock": round(self._clock, 9),
            }


result_cache = ResultCache()
router_result_cache = ResultCache(prefix="router.result_cache")


def invalidate_all(index_root: Optional[str] = None) -> int:
    """Scoped invalidation across BOTH cache levels — the collection
    manager's one hook: a refresh/optimize/delete of an index drops its
    serve-level entries AND every router-level entry whose fan-out
    touched it (either join side)."""
    return result_cache.invalidate(index_root) + router_result_cache.invalidate(
        index_root
    )


def budget_share_bytes(share: float) -> int:
    """The cache-wide byte cap: ``share`` of the SAME env HBM budget the
    residency ladder divides (docs/13). Shares are clamped by conf to
    [0, 0.5] — the cache can never claim more than the slab reservation
    cap."""
    from ..exec.bytecache import env_mb

    total = env_mb("HYPERSPACE_TPU_HBM_BUDGET_MB", 4096)
    return max(int(total * float(share)), 1)


def result_key(
    plan, version_token: tuple, signature: Optional[tuple] = None
) -> tuple:
    """The ONE memo-key convention: the serve plan cache's value-level
    signature (literals + file snapshots) plus the full version token
    (index generation + conf). ``signature`` accepts a caller-
    precomputed ``plan_signature(plan)`` so the server path shares one
    tree walk with the plan cache."""
    if signature is None:
        from ..serve.plan_cache import plan_signature

        signature = plan_signature(plan)
    return (signature, version_token)


def result_roots(optimized_plan) -> Tuple[str, ...]:
    """Scoped-invalidation anchors of the OPTIMIZED plan (what actually
    served the result) — the fingerprint module's ONE anchor convention,
    shared with the pipeline cache."""
    from .fingerprint import index_roots

    return index_roots(optimized_plan)


# Register both instances on the residency ladder: their bytes charge
# against the one HBM budget and shed before anything else (tiers is the
# ladder's home; import is cycle-free — residency never imports compile).
from ..residency import tiers as _tiers  # noqa: E402

_tiers.register_claimant("result_cache", result_cache)
_tiers.register_claimant("router_result_cache", router_result_cache)
