"""The compiled-pipeline cache: (fingerprint, index-log version token,
conf) -> CompiledPipeline.

This replaces the serve tier's per-scan executable reuse with WHOLE
pipelines: the serve plan cache still memoizes plan optimization, and
this cache memoizes the lowering/routing above execution — keyed so that
snapshot-pinned reads (PR 9) serve whole compiled pipelines wholesale.
Invalidation rides the same tokens the plan cache pins:

* the FINGERPRINT carries every index leaf's (name, log id) and every
  source leaf's file snapshot, so any refresh/optimize/create/delete
  that touches a leaf re-keys naturally;
* the VERSION TOKEN (the server passes the ticket's pinned index-log
  snapshot) keeps two pinned generations of one structure apart during
  a concurrent refresh;
* ``invalidate(index_root)`` drops entries scoped to a rewritten
  index's directory — a JOIN pipeline carries both sides' roots, so it
  drops on EITHER side's change (mirroring invalidate_joins); the
  collection manager calls this from refresh/optimize/delete.

Lock discipline: every ``_pipelines``/``_epoch`` mutation happens under
``_lock`` (enforced by hslint HS012's compile-cache extension); lookups
that MISS lower OUTSIDE the lock (lowering does IO-free probes but is
not free) and re-check under the lock before registering. Unlike the
residency caches, entries hold no device arrays, so a registration that
races reset() is harmless — the epoch exists for observability and to
keep the HS012 structural scope honest.
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict
from typing import Optional

from ..telemetry.metrics import metrics
from ..telemetry.trace import annotate, span
from .pipeline import CompiledPipeline

# per-conf-object memo of the serialized token, keyed on the conf's
# mutation generation: the token is needed on EVERY execute (cache hits
# included) and re-sorting the whole conf dict per query would sit on
# the hot path the pipeline cache exists to shorten
_conf_token_memo: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_conf_token_lock = threading.Lock()


def _conf_token(conf) -> tuple:
    gen = getattr(conf, "generation", None)
    if gen is not None:
        with _conf_token_lock:
            hit = _conf_token_memo.get(conf)
            if hit is not None and hit[0] == gen:
                return hit[1]
    token = tuple(sorted((k, str(v)) for k, v in conf.as_dict().items()))
    if gen is not None:
        with _conf_token_lock:
            _conf_token_memo[conf] = (gen, token)
    return token


class PipelineCache:
    """Bounded LRU of compiled pipelines (module note)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pipelines: "OrderedDict[tuple, CompiledPipeline]" = (
            OrderedDict()
        )
        self._epoch = 0

    def get_or_lower(
        self, plan, executor, version_token: Optional[tuple] = None
    ) -> Optional[CompiledPipeline]:
        """The pipeline for ``plan`` under ``executor``'s conf/mesh —
        cached by structural fingerprint, lowered on miss. None when the
        fingerprint cannot be computed (the caller interprets)."""
        from .fingerprint import plan_fingerprint
        from .lowering import lower

        conf = executor.conf
        try:
            fp = plan_fingerprint(plan, executor.mesh)
        except Exception:  # noqa: BLE001 - fingerprint error: interpret
            metrics.incr("compile.fingerprint_error")
            return None
        key = (fp, version_token, _conf_token(conf))
        with self._lock:
            hit = self._pipelines.get(key)
            if hit is not None:
                self._pipelines.move_to_end(key)
        if hit is not None:
            metrics.incr("compile.cache.hit")
            annotate(compile_cache="hit")
            return hit
        metrics.incr("compile.cache.miss")
        annotate(compile_cache="miss")
        with span("compile.lower"):
            pipeline = lower(plan, conf, executor.mesh, fingerprint=fp)
        max_entries = max(int(conf.compile_cache_entries()), 1)
        with self._lock:
            racer = self._pipelines.get(key)
            if racer is not None:
                return racer  # a concurrent miss lowered first: share its
            pipeline.cache = self
            pipeline.cache_key = key
            self._pipelines[key] = pipeline
            while len(self._pipelines) > max_entries:
                self._pipelines.popitem(last=False)
                metrics.incr("compile.cache.evicted")
        return pipeline

    def forget(self, pipeline: CompiledPipeline) -> None:
        """Evict exactly ``pipeline``'s entry (device loss mid-dispatch)
        — the rest of the cache keeps serving."""
        key = pipeline.cache_key
        if key is None:
            return
        with self._lock:
            if self._pipelines.get(key) is pipeline:
                del self._pipelines[key]

    def invalidate(self, index_root: Optional[str] = None) -> int:
        """Drop pipelines whose index leaves live under ``index_root``
        (None drops everything). Returns the number dropped."""
        prefix = None
        if index_root is not None:
            prefix = str(index_root).rstrip("/") + "/"
        with self._lock:
            if prefix is None:
                n = len(self._pipelines)
                self._pipelines.clear()
            else:
                doomed = [
                    k
                    for k, p in self._pipelines.items()
                    if p.matches_root(prefix)
                ]
                for k in doomed:
                    del self._pipelines[k]
                n = len(doomed)
        if n:
            metrics.incr("compile.cache.invalidated", n)
        return n

    def reset(self) -> None:
        with self._lock:
            self._pipelines.clear()
            self._epoch += 1

    def snapshot(self) -> dict:
        with self._lock:
            entries = list(self._pipelines.values())
        kinds: dict = {}
        for p in entries:
            kinds[p.kind] = kinds.get(p.kind, 0) + 1
        return {"entries": len(entries), "kinds": kinds}


pipeline_cache = PipelineCache()
