"""Structural pipeline fingerprints: the compiled-pipeline cache key.

A fingerprint captures everything that decides HOW a plan executes —
operator shapes, predicate STRUCTURE, projections, each index leaf's
logged version, each source leaf's file snapshot — while masking literal
VALUES out. Two queries that differ only in literals share a fingerprint
and therefore a CompiledPipeline; the fused dispatch they reach feeds
literals as traced int32 operands into the structure-keyed executables
(exec.hbm_cache's batched counts machinery), so a serving burst of fresh
keys reuses one compiled program instead of recompiling per literal.

Residency is deliberately NOT part of the structural walk for scan and
hybrid arms: the pipeline's fused legs resolve residency per dispatch
through the SAME shared eligibility procedures the interpreter uses
(resident_for / resolve_hybrid_residency), so a tier change — populate,
evict, device loss — degrades or upgrades the serving rung without
invalidating the program. The tier a pipeline last served on rides the
pipeline as observability (explain(verbose)), not as a key. Join shapes
are the exception: batch classification resolved a REGION generation, so
the fingerprint folds both caches' join_region_version — a region
register/evict re-lowers instead of serving a stale routing decision
(the same rule the serve plan cache's version token follows).
"""

from __future__ import annotations

from typing import Tuple

from ..plan.expr import And, Cmp, Col, Expr, In, Lit, Not, Or
from ..plan.ir import (
    Aggregate,
    BucketUnion,
    Filter,
    IndexScan,
    Join,
    LogicalPlan,
    Project,
    Repartition,
    Scan,
    Union,
)


def expr_structure(e: Expr) -> str:
    """Canonical structure string of a USER predicate with literal values
    masked — tolerant of every plan.expr node (the narrowed twin in
    exec.hbm_cache covers only post-narrowing shapes). IN keeps its value
    COUNT: narrowing expands IN into an OR chain per value, so two INs of
    different arity compile different executables."""
    if isinstance(e, (And, Or)):
        tag = "&" if isinstance(e, And) else "|"
        return f"({expr_structure(e.left)}{tag}{expr_structure(e.right)})"
    if isinstance(e, Not):
        return f"~({expr_structure(e.child)})"
    if isinstance(e, Cmp):
        return f"({expr_structure(e.left)} {e.op} {expr_structure(e.right)})"
    if isinstance(e, In):
        return f"in({expr_structure(e.child)},#{len(e.values)})"
    if isinstance(e, Col):
        return f"col({e.name})"
    if isinstance(e, Lit):
        return "?"
    # future expression nodes fingerprint by repr — conservative (repr
    # includes literals, so unknown shapes never falsely share)
    return repr(e)


def _node_sig(n: LogicalPlan) -> Tuple:
    if isinstance(n, Filter):
        return ("F", expr_structure(n.condition))
    if isinstance(n, Project):
        return ("P", tuple(n.columns))
    if isinstance(n, IndexScan):
        # (name, log id) IS the leaf's index-log version: a refresh or
        # optimize bumps the id, so pipelines never outlive the index
        # generation they were lowered against
        return (
            "I",
            n.entry.name,
            n.entry.id,
            tuple(n.required_columns),
            n.use_bucket_spec,
        )
    if isinstance(n, Scan):
        rel = n.relation
        return (
            "S",
            rel.file_format,
            tuple(rel.root_paths),
            tuple((f.name, f.size, f.modified_time) for f in rel.files),
        )
    if isinstance(n, Join):
        return ("J", expr_structure(n.condition), n.join_type)
    if isinstance(n, Aggregate):
        return (
            "A",
            tuple(n.group_by),
            tuple((a.fn, a.column, a.name) for a in n.aggs),
        )
    if isinstance(n, BucketUnion):
        cols, nb = n.bucket_spec
        return ("BU", tuple(cols), nb)
    if isinstance(n, Repartition):
        return ("R", tuple(n.columns), n.num_buckets)
    if isinstance(n, Union):
        return ("U",)
    return (n.node_name,)


def _walk(n: LogicalPlan) -> Tuple:
    return (_node_sig(n), tuple(_walk(c) for c in n.children))


def plan_fingerprint(plan: LogicalPlan, mesh=None) -> Tuple:
    """The structural fingerprint of an optimized plan subtree. Folds the
    mesh topology (a mesh session lowers differently) and — for plans
    holding a Join — both residency caches' join-region generations
    (module note)."""
    parts: list = [_walk(plan)]
    parts.append(("mesh", int(mesh.devices.size) if mesh is not None else 0))
    if plan.collect(lambda n: isinstance(n, Join)):
        from ..exec.hbm_cache import hbm_cache
        from ..exec.mesh_cache import mesh_cache

        parts.append(
            (
                "join_regions",
                hbm_cache.join_region_version(),
                mesh_cache.join_region_version(),
            )
        )
    return tuple(parts)


def index_roots(plan: LogicalPlan) -> Tuple[str, ...]:
    """One sample data-file path per index leaf — the scoped-invalidation
    anchors of BOTH compile caches (collection_manager matches refresh/
    optimize/delete roots against these by prefix, the invalidate_joins
    rule). A join pipeline carries BOTH sides' leaves, so it drops on
    EITHER side's change. The ONE anchor convention — the pipeline cache
    and the result cache must never scope differently."""
    roots = []
    for n in plan.collect(lambda n: isinstance(n, IndexScan)):
        files = n.entry.content.files()
        if files:
            roots.append(str(files[0]))
    return tuple(roots)


def batch_fingerprint(plan: LogicalPlan) -> Tuple:
    """The COARSE fingerprint the serve micro-batcher folds into its
    batch keys: shape class + each index leaf's version + projection and
    predicate COLUMN SETS. Deliberately coarser than plan_fingerprint —
    the stacked batch executable is keyed per-slot on full predicate
    structure already (exec.hbm_cache._batched_counts_fn), so two
    structures over the same resident column set may still share a
    dispatch; folding full structure here would only shrink batches."""
    leaves = tuple(
        ("I", n.entry.name, n.entry.id)
        for n in plan.collect(lambda n: isinstance(n, IndexScan))
    )
    preds = tuple(
        frozenset(n.condition.columns())
        for n in plan.collect(lambda n: isinstance(n, Filter))
    )
    return (leaves, preds, frozenset(plan.output_columns()))
