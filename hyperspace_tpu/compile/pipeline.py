"""CompiledPipeline: one lowered plan subtree, runnable against any
literal binding that shares its structural fingerprint.

A pipeline stores ROUTING, not values: which fused arm serves the
subtree and how to re-bind per-query operands (predicate literals,
projection order) from the concrete plan each run receives. The fused
arms reach the structure-keyed executables (literals as traced int32
operands), so every run of a pipeline — across a whole serving burst of
distinct keys — shares one compiled device program and ships home at
most ONE D2H transfer between plan arms (the count vector / finished
group table); the interpreter is the fallback leg for every per-query
eligibility miss, with results identical by the shared-procedure
argument (the fused arms and the interpreter call the same resolution
and host-leg code).

Device loss mid-fused-dispatch degrades exactly like the interpreter's
fused arms (the shared procedures drop the resident state and latch the
QUERY host-side), and additionally evicts THIS pipeline's cache entry —
not the whole cache — so the next structurally-equal query re-lowers
against post-loss residency instead of re-entering a dead routing
decision.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

from ..storage.columnar import ColumnarBatch
from ..telemetry.metrics import metrics
from ..telemetry.trace import span

# the device-failure counters every fused arm's degradation path bumps
# (exec.scan / exec.executor / exec.join_residency): a run that moved any
# of them hit a dead device mid-dispatch. Read from the run's SCOPED
# child registry, never the global one — two concurrent queries' device
# failures must not cross-attribute (a global delta would evict a
# healthy pipeline because an unrelated table died on another worker)
_DEVICE_FAIL_COUNTERS = (
    "scan.resident.device_failed",
    "scan.resident_mesh.device_failed",
    "scan.resident_join.device_failed",
)


def _device_failures(registry) -> int:
    return sum(registry.counter(c) for c in _DEVICE_FAIL_COUNTERS)


class CompiledPipeline:
    """One lowered subtree. ``run(plan, executor)`` executes a concrete
    plan whose fingerprint equals this pipeline's."""

    def __init__(
        self,
        kind: str,
        fingerprint: Optional[tuple],
        tier: str,
        index_roots: Tuple[str, ...],
        boundary: tuple,
    ):
        self.kind = kind
        self.fingerprint = fingerprint
        self.tier = tier
        self.index_roots = index_roots
        self.boundary = boundary
        # short stable id of the structural fingerprint — the
        # "which executable" label every trace span and describe() carry
        # (the full tuple is unwieldy in a span tree)
        import hashlib

        self.fingerprint_id = (
            hashlib.blake2s(
                repr(fingerprint).encode("utf-8"), digest_size=4
            ).hexdigest()
            if fingerprint is not None
            else None
        )
        # set by PipelineCache when the pipeline is cached; forget-on-
        # device-loss needs them to evict exactly one entry
        self.cache = None
        self.cache_key = None
        # observability tallies, mutated by concurrent runs: guarded by
        # their own lock (a pipeline is shared across serve workers)
        self._stats_lock = threading.Lock()
        self.runs = 0
        self.fused_dispatches = 0

    # -- observability -------------------------------------------------------
    def describe(self) -> dict:
        return {
            "kind": self.kind,
            "tier": self.tier,
            "fingerprint": self.fingerprint_id,
            "boundary": list(self.boundary),
            "runs": self.runs,
            "fused_dispatches": self.fused_dispatches,
        }

    def matches_root(self, prefix: str) -> bool:
        return any(p.startswith(prefix) for p in self.index_roots)

    # -- execution -----------------------------------------------------------
    def run(self, plan, executor) -> ColumnarBatch:
        with self._stats_lock:
            self.runs += 1
        metrics.incr(f"compile.run.{self.kind}")
        # a scoped child registry attributes THIS run's recordings (the
        # fused arms record on this thread; the union host legs copy the
        # context into their pool threads) — global counter deltas would
        # cross-talk between concurrent queries
        with metrics.scoped() as run_metrics:
            try:
                # the trace's "which executable" span: kind + residency
                # tier at lowering + the structural fingerprint id
                with span(
                    "compile.pipeline_run",
                    kind=self.kind,
                    tier=self.tier,
                    fingerprint=self.fingerprint_id,
                ), metrics.timer("compile.pipeline_run"):
                    out = self._run_kind(plan, executor)
            finally:
                with self._stats_lock:
                    self.fused_dispatches += run_metrics.counter(
                        "compile.fused.dispatches"
                    )
                if _device_failures(run_metrics) > 0:
                    # the query already latched host-side through the
                    # shared degradation path; evict ONLY this pipeline
                    # so the next structurally-equal query re-lowers
                    # against post-loss residency (fault-injection-
                    # tested)
                    metrics.incr("compile.pipeline.dropped_on_device_loss")
                    if self.cache is not None:
                        self.cache.forget(self)
        return out

    def _run_kind(self, plan, executor) -> ColumnarBatch:
        from .lowering import classify_shape

        if self.kind == "interpret":
            return executor._exec(plan, predicate=None)
        shape = classify_shape(plan, executor.mesh)
        if shape.kind != self.kind:
            # fingerprint/classification drift (cannot happen for equal
            # fingerprints; guards a future structural-walk change):
            # interpret exactly
            metrics.incr("compile.shape_drift")
            return executor._exec(plan, predicate=None)
        if self.kind == "scan":
            out = self._run_scan(shape, executor)
            return _apply_projects(out, shape.projects)
        if self.kind == "agg_scan":
            out = self._run_agg_scan(shape, executor)
            return _apply_projects(out, shape.projects)
        if self.kind == "hybrid":
            out = self._run_hybrid(shape, executor)
            return _apply_projects(out, shape.projects)
        if self.kind == "join_agg":
            out = self._run_join_agg(shape, executor)
            return _apply_projects(out, shape.projects)
        if self.kind == "join_shuffle":
            out = self._run_join_shuffle(shape, executor)
            return _apply_projects(out, shape.projects)
        raise AssertionError(f"unknown pipeline kind {self.kind!r}")

    def _run_scan(self, shape, executor) -> ColumnarBatch:
        """The fused scan arm: exec.scan.index_scan with the structure-
        keyed counts dispatch — the ONE serving procedure (residency
        resolution, zone gate, host legs, empty-schema handling) the
        interpreter uses, so per-query eligibility misses degrade
        identically; only the executable keying differs (literals traced
        instead of baked in). Mesh sessions route the mesh arm."""
        from ..exec.scan import index_scan

        if executor.mesh is not None:
            return self._run_scan_mesh(shape, executor)
        scan = shape.scan
        entry = scan.entry
        return index_scan(
            entry.content.files(),
            list(scan.required_columns),
            shape.condition,
            device=executor.device,
            indexed_columns=entry.indexed_columns,
            dtypes=entry.schema,
            num_buckets=entry.num_buckets,
            structure_keyed=True,
        )

    def _run_scan_mesh(self, shape, executor) -> ColumnarBatch:
        """The fused MESH scan arm: when the shards are resident, the
        counts dispatch rides the structure-keyed shard_map batched
        entry (N=1, literals as traced operands — a fresh-literal burst
        shares one executable, the single-chip rule on the mesh); every
        miss — no table, narrow failure, device loss — falls to the
        interpreter's distributed scan, which owns population scheduling
        and the ship-per-query path."""
        from pathlib import Path

        from ..exec.mesh_cache import mesh_cache
        from ..exec.scan import _empty_result, prune_index_files

        scan = shape.scan
        entry = scan.entry
        predicate = shape.condition
        # resolve against the version's FULL file list (a table always
        # covers it, so pruning cannot change the hit outcome) and prune
        # only on a hit: the common miss then pays ONE registry probe —
        # which early-outs on an empty cache — before handing the query
        # to the interpreter's distributed scan, instead of re-running
        # file pruning the fallback repeats anyway
        all_files = entry.content.files()
        counts = None
        table = None
        files: list = []
        if all_files:
            table = mesh_cache.resident_for(
                all_files, sorted(predicate.columns()), executor.mesh
            )
        if table is not None:
            # the query's pruned subset restricts the host leg's reads
            files = prune_index_files(
                [Path(p) for p in all_files],
                predicate,
                entry.indexed_columns,
                entry.schema,
                entry.num_buckets,
            )
            try:
                with span(
                    "scan.device_dispatch",
                    tier=getattr(table, "tier", "resident"),
                    structure_keyed=True,
                    mesh=table.n_devices,
                ):
                    m = mesh_cache.block_counts_batch(
                        table, [predicate], metric_ns="compile.fused"
                    )
                counts = None if m is None else m[0]
            except Exception:  # noqa: BLE001 - device loss degrades
                mesh_cache.drop(table)
                metrics.incr("scan.resident_mesh.device_failed")
                counts = None
        if counts is None:
            return executor._exec_index_scan_distributed(scan, predicate)
        metrics.incr("scan.files_read", len(files))
        parts = mesh_cache.collect_parts(
            table, files, list(scan.required_columns), predicate, counts
        )
        if parts:
            return ColumnarBatch.concat(parts)
        # the ONE empty-result construction (exec.scan) — the host and
        # interpreter legs build theirs through the same helper
        return _empty_result(
            files, list(scan.required_columns), entry.schema
        )

    def _run_agg_scan(self, shape, executor) -> ColumnarBatch:
        """The agg_scan arm: DEVICE aggregation first — one executable
        fuses the predicate mask with dense-key segment reductions and
        ships the FINISHED group table home (exec.scan_agg; the PR-5
        resident_join_agg machinery generalized to single-table
        aggregates). Device-ineligible specs fall to the count-vector
        scan + host hash-aggregate tail, each decline counted under
        compile.agg.declined.<reason> — never a silent host tail."""
        from ..exec.aggregate import hash_aggregate

        fused = self._try_device_agg(shape, executor)
        if fused is not None:
            return fused
        if executor.mesh is not None:
            # the interpreter's whole Aggregate procedure: the mesh tail
            # keeps its two-phase distributed aggregate (per-device
            # partials, psum-style host merge) and its path counters —
            # a decline must not demote the mesh to gather-then-hash
            return executor._exec_aggregate(shape.agg, None)
        out = self._run_scan(shape, executor)
        out = _apply_projects(out, shape.inner_projects)
        return hash_aggregate(
            out, list(shape.agg.group_by), list(shape.agg.aggs)
        )

    def _try_device_agg(self, shape, executor) -> Optional[ColumnarBatch]:
        """The device-aggregation attempt, or None with its decline
        counted. Population: a no_table miss schedules the predicate AND
        group/agg columns, so the NEXT structurally-equal query
        aggregates on device. No selectivity zone gate applies — the
        device-agg host leg reads nothing, so a broad predicate has no
        host-read cost for the gate to protect (exec.scan_agg note)."""
        group_by = list(shape.agg.group_by)
        aggs = list(shape.agg.aggs)

        def decline(reason: str):
            metrics.incr(f"compile.agg.declined.{reason}")
            return None

        if not group_by:
            # the global-aggregate empty-input contract (one NULL-ish
            # row) belongs to the host tail
            return decline("shape")
        need = list(
            dict.fromkeys(group_by + [a.column for a in aggs if a.column])
        )
        # an inner projection that starves the aggregate must raise on
        # the host path, not silently aggregate on device
        for p in shape.inner_projects:
            if not set(need) <= set(p.columns):
                return decline("shape")
        entry = shape.scan.entry
        if any(c not in entry.schema for c in need):
            return decline("column")
        all_files = entry.content.files()
        if not all_files:
            return decline("no_table")
        pred_cols = sorted(shape.condition.columns())
        want_cols = sorted(set(pred_cols) | set(need))
        if executor.mesh is not None:
            from ..exec.mesh_cache import mesh_cache as cache

            table = cache.resident_for(all_files, want_cols, executor.mesh)
            fail_metric = "scan.resident_mesh.device_failed"
        else:
            from ..exec.hbm_cache import hbm_cache as cache

            table = cache.resident_for(all_files, want_cols)
            fail_metric = "scan.resident.device_failed"
        if table is None:
            if cache.auto_enabled():
                if executor.mesh is not None:
                    cache.note_touch(all_files, want_cols, executor.mesh)
                else:
                    cache.note_touch(all_files, want_cols)
            return decline("no_table")
        try:
            out, reason = cache.agg_scan(
                table, shape.condition, group_by, aggs
            )
        except Exception:  # noqa: BLE001 - device loss degrades
            # drop the table and latch THIS query host through the host
            # tail; the scoped failure counter also evicts this pipeline
            # (run()'s device-failure check)
            cache.drop(table)
            metrics.incr(fail_metric)
            return decline("device")
        if out is None:
            return decline(reason)
        metrics.incr("compile.fused.dispatches")
        metrics.incr("compile.agg.device")
        return out

    def _run_hybrid(self, shape, executor) -> ColumnarBatch:
        """The fused hybrid arm on the STRUCTURE-KEYED batched entry
        (structure_keyed=True routes hybrid_block_counts_batch N=1 with
        literals as traced operands, so a fresh-literal hybrid burst
        shares ONE executable — the same trick the scan arm rode since
        PR 10; the dispatch itself counts compile.fused.dispatches),
        falling to the concurrent per-side host union — the split entry
        points guarantee the fallback never re-runs the residency
        resolution (no double-counted declines)."""
        fused = executor._try_resident_hybrid(
            shape.union, shape.condition, structure_keyed=True
        )
        if fused is not None:
            return fused
        columns = (
            list(shape.projects[-1].columns) if shape.projects else None
        )
        return executor._exec_union_host(
            shape.union, shape.condition, columns
        )

    def _run_join_agg(self, shape, executor) -> ColumnarBatch:
        """The fused aggregate-join arm: the executor's Aggregate
        procedure (resident fused region dispatch first — single-chip
        AND mesh — then the host range-fusion, then gather+hash), as one
        lowered pipeline stage. Whether THIS run dispatched fused is
        read from a scoped child registry — a global counter diff would
        misattribute a concurrent query's dispatch (the same rule the
        device-failure check follows)."""
        with metrics.scoped() as jm:
            out = executor._exec_aggregate(shape.agg, None)
        if (
            jm.counter("scan.path.resident_join_agg")
            + jm.counter("scan.path.resident_join_agg_mesh")
            > 0
        ):
            metrics.incr("compile.fused.dispatches")
        return out


    def _run_join_shuffle(self, shape, executor) -> ColumnarBatch:
        """The shuffle-join arm: the executor's whole Join procedure
        (shuffle eligibility + planner + exchange, exact host join on
        every decline) as one lowered stage. Whether THIS run actually
        rode the ICI exchange is read from a scoped child registry —
        the _run_join_agg attribution rule."""
        with metrics.scoped() as jm:
            out = executor._exec_join(shape.join)
        if jm.counter("scan.path.resident_join_shuffle") > 0:
            metrics.incr("compile.fused.dispatches")
        return out


def _apply_projects(batch: ColumnarBatch, projects) -> ColumnarBatch:
    """Apply a collected Project stack innermost-first (``projects`` is
    outermost-first, the classify_shape order) — mirrors the
    interpreter's bottom-up select chain."""
    for p in reversed(projects):
        batch = batch.select(list(p.columns))
    return batch
