"""Plan -> CompiledPipeline lowering: the residency-aware fusion pass.

The lowering walks an OPTIMIZED plan subtree and decides, per subtree,
which arm serves it fused — routing through the existing ONE-shared
eligibility procedures (exec.scan's resident branch, exec.delta's
resolve_hybrid_residency, exec.join_residency's resolve_join_residency)
rather than a parallel copy — and which falls to the exact host legs.
The interpreter (exec.executor._exec) is the fallback leg of every
pipeline: a shape the lowering doesn't recognize, a mesh arm it declines,
or a per-query eligibility miss all land there with identical results.

Shape classes (single-chip AND mesh unless noted):

* ``scan``       — ``[Project]* Filter IndexScan``: the filter-pushdown
  pipeline serves as ONE fused mask+count dispatch whose executable is
  keyed on predicate STRUCTURE with literals as traced operands
  (exec.scan.index_scan structure_keyed=True; mesh sessions ride the
  structure-keyed shard_map batched entry the same way), host legs
  exact.
* ``agg_scan``   — ``[Project]* Aggregate([Project]* Filter IndexScan)``:
  the group-by lowers ONTO THE DEVICE when the resident table covers
  the group/agg columns (exec.scan_agg — mask + dense-key segment
  sum/count/min/max in one executable, mesh partials psum-merged; ONE
  D2H ships the finished group table, no candidate blocks); device-
  ineligible specs route the count-vector scan + host hash-aggregate
  tail with a ``compile.agg.declined.<reason>`` counter.
* ``hybrid``     — ``[Project]* Filter Union(...)`` (single-chip): the
  delta-resident hybrid arm on the STRUCTURE-KEYED batched entry
  (fused base+delta dispatch, deletion bitmask on device, literals as
  traced operands — a fresh-literal hybrid burst shares one
  executable) with the concurrent per-side host union as fallback.
  Mesh hybrids stay with the interpreter's literal-keyed fused arm.
* ``join_agg``   — ``[Project]* Aggregate([Project](Join))``: the
  resident aggregate-join arm (single-chip AND mesh — the PR-5/8 fused
  kernels are the lowering targets), host range-fusion fallback.
* ``join_shuffle`` — ``[Project]* Join`` over bucketed sides with
  MISMATCHED bucket counts, mesh only: the ICI all-to-all shuffle
  repartitions the smaller side into the other's bucket space and the
  co-partitioned SMJ arms serve (distributed/shuffle.py); the planner
  and every exchange failure decline to the exact host join.
* ``interpret``  — everything else: the per-operator interpreter.

Lowering is cheap (a shape walk plus counter-free registry probes for
the advisory tier label) and NEVER raises — any internal error lowers to
``interpret``, counted under ``compile.lower_error``.
"""

from __future__ import annotations

from typing import List, Optional

from ..plan.ir import (
    Aggregate,
    Filter,
    IndexScan,
    Join,
    LogicalPlan,
    Project,
    Union,
)
from ..telemetry.metrics import metrics
from .pipeline import CompiledPipeline


class Shape:
    """The classified shape of a plan: which pipeline kind it lowers to
    plus the per-query operands re-bound at run time (projects stack,
    filter condition, leaf nodes). Literal-value-free by construction —
    run() re-extracts operands from the CONCRETE plan it is given."""

    __slots__ = (
        "kind",
        "projects",
        "condition",
        "scan",
        "union",
        "agg",
        "inner_projects",
        "join",
    )

    def __init__(
        self,
        kind: str,
        projects: Optional[List[Project]] = None,
        condition=None,
        scan: Optional[IndexScan] = None,
        union: Optional[Union] = None,
        agg: Optional[Aggregate] = None,
        inner_projects: Optional[List[Project]] = None,
        join: Optional[Join] = None,
    ):
        self.kind = kind
        self.projects = projects or []
        self.condition = condition
        self.scan = scan
        self.union = union
        self.agg = agg
        self.inner_projects = inner_projects or []
        self.join = join


def classify_shape(plan: LogicalPlan, mesh=None) -> Shape:
    """Structural classification — pure, no IO, no counters. Shared by
    lower() and CompiledPipeline.run()'s per-query operand re-binding
    (two plans with equal fingerprints classify identically, so the
    re-bind can never route a query differently than its pipeline)."""
    projects: List[Project] = []
    node = plan
    while isinstance(node, Project):
        projects.append(node)
        node = node.child
    if isinstance(node, Aggregate):
        inner = node.child
        inner_projects: List[Project] = []
        while isinstance(inner, Project):
            inner_projects.append(inner)
            inner = inner.child
        if isinstance(inner, Join):
            return Shape("join_agg", projects, agg=node)
        if isinstance(inner, Filter) and isinstance(inner.child, IndexScan):
            return Shape(
                "agg_scan",
                projects,
                inner.condition,
                inner.child,
                agg=node,
                inner_projects=inner_projects,
            )
        return Shape("interpret")
    if isinstance(node, Filter):
        child = node.child
        if isinstance(child, IndexScan):
            return Shape("scan", projects, node.condition, child)
        if isinstance(child, Union) and mesh is None:
            # mesh hybrids keep the interpreter's literal-keyed fused
            # arm — the structure-keyed hybrid batch entry is single-chip
            return Shape("hybrid", projects, node.condition, union=child)
    if isinstance(node, Join) and mesh is not None:
        # both sides bucketed but with MISMATCHED bucket counts: the
        # shuffle-repartition join (distributed/shuffle.py). Metadata
        # walk only — the executor's shuffle arm re-runs the full
        # eligibility (key sets, planner economics) per query and
        # declines to the exact host join identically.
        from ..exec.executor import bucketed_meta

        lm = bucketed_meta(node.left)
        rm = bucketed_meta(node.right)
        if (
            lm is not None
            and rm is not None
            and lm.entry.num_buckets != rm.entry.num_buckets
        ):
            return Shape("join_shuffle", projects, join=node)
    return Shape("interpret")


def _tier_label(shape: Shape, mesh=None) -> str:
    """Advisory residency label for the pipeline (explain/observability):
    which rung the fused arm WOULD serve on right now. Counter-free —
    registry probes only, never the counting eligibility procedures (a
    lowering must not skew per-query decline counters)."""
    try:
        if shape.kind in ("scan", "agg_scan") and shape.scan is not None:
            entry = shape.scan.entry
            pred_cols = sorted(shape.condition.columns())
            if shape.kind == "agg_scan" and shape.agg is not None:
                # the device-agg arm needs the GROUP/AGG columns resident
                # too — labeling from predicate coverage alone would
                # print a device tier above an "Aggregate ran: host hash"
                # line (explain contradiction)
                pred_cols = sorted(
                    set(pred_cols)
                    | set(shape.agg.group_by)
                    | {a.column for a in shape.agg.aggs if a.column}
                )
            if mesh is not None:
                from ..exec.mesh_cache import mesh_cache

                table = mesh_cache.resident_for(
                    entry.content.files(), pred_cols, mesh
                )
            else:
                from ..exec.hbm_cache import hbm_cache

                table = hbm_cache.resident_for(
                    entry.content.files(), pred_cols
                )
            return getattr(table, "tier", "resident") if table else "host"
        if shape.kind == "hybrid":
            from ..exec.hbm_cache import hbm_cache
            from ..plan.rules.hybrid_scan import parse_hybrid_union

            info = parse_hybrid_union(shape.union)
            if info is None:
                return "host"
            table = hbm_cache.resident_for(
                info.entry.content.files(),
                sorted(shape.condition.columns()),
            )
            return getattr(table, "tier", "resident") if table else "host"
        if shape.kind == "join_agg":
            from ..exec.hbm_cache import hbm_cache
            from ..exec.mesh_cache import mesh_cache

            return (
                "join_region"
                if (
                    hbm_cache.snapshot_joins()["regions"]
                    or mesh_cache.snapshot_joins()["regions"]
                )
                else "host"
            )
        if shape.kind == "join_shuffle":
            # mesh presence IS the classification gate; the per-query
            # economics (planner) may still decline to host
            return "mesh"
    except Exception:  # noqa: BLE001 - the label is advisory only
        metrics.incr("compile.tier_probe_error")
    return "host"


def lower(
    plan: LogicalPlan, conf, mesh=None, fingerprint: Optional[tuple] = None
) -> CompiledPipeline:
    """Lower ``plan`` to a CompiledPipeline. Never raises: an internal
    error lowers to the interpreter pipeline (counted)."""
    from .fingerprint import index_roots

    try:
        with metrics.timer("compile.lower"):
            shape = classify_shape(plan, mesh)
            pipeline = CompiledPipeline(
                kind=shape.kind,
                fingerprint=fingerprint,
                tier=_tier_label(shape, mesh),
                index_roots=index_roots(plan),
                boundary=_boundary(plan, shape),
            )
        metrics.incr("compile.lowered")
        metrics.incr(f"compile.lowered.{shape.kind}")
        return pipeline
    except Exception:  # noqa: BLE001 - lowering must never fail a query
        metrics.incr("compile.lower_error")
        return CompiledPipeline(
            kind="interpret",
            fingerprint=fingerprint,
            tier="host",
            index_roots=(),
            boundary=("interpret: lowering error",),
        )


def _boundary(plan: LogicalPlan, shape: Shape) -> tuple:
    """Human-readable fused-subtree boundary for explain(verbose): which
    operators ride the fused dispatch and where the host legs begin."""
    if shape.kind == "interpret":
        return ("interpret: " + plan.node_name + " (per-operator)",)
    lines = [f"fused[{shape.kind}]:"]
    fused_nodes = {
        "scan": "Filter→IndexScan (one mask+count dispatch)",
        "agg_scan": (
            "Aggregate→Filter→IndexScan (one dispatch: device "
            "segment-agg, host hash tail on decline)"
        ),
        "hybrid": "Filter→Union base+delta (one fused dispatch)",
        "join_agg": "Aggregate→Join (resident region dispatch)",
        "join_shuffle": (
            "Join (ICI all-to-all repartition → co-partitioned SMJ; "
            "planner may decline to host)"
        ),
    }
    lines.append("  device: " + fused_nodes[shape.kind])
    lines.append("  host legs: candidate-block reads + exact predicates")
    return tuple(lines)
