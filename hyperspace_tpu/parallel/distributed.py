"""Multi-host scaling: the DCN/ICI story.

Parity: the reference scales out by running on a Spark cluster — its
communication backend is Spark's netty shuffle service + broadcast
(SURVEY.md §5.8: there is no NCCL/MPI in the repo; the executor pool IS
the distributed runtime). Here the distributed runtime is JAX/XLA's:

* **within a slice**: the bucket-parallel mesh (parallel.mesh) spans the
  slice's chips; the build's hash-repartition rides the ICI
  ``all_to_all`` and bucketed queries are collective-free by placement
  (exec.distributed).
* **across slices / hosts (single controller)**: nothing changes in this
  codebase — ``make_mesh()`` over ``jax.devices()`` already spans every
  addressable device, and XLA routes each collective over ICI within a
  slice and DCN across slices automatically. That is the whole point of
  expressing the shuffle as a named-axis collective instead of explicit
  NCCL calls: topology is the compiler's problem.
* **multi-controller (one process per host)**: call
  ``initialize_multihost()`` first — the DCN control plane
  (jax.distributed) makes ``jax.devices()`` global. The query side works
  unchanged (index files live on shared storage; every process can read
  any bucket). The build side's multi-controller ingest is
  ``ops.build.build_partition_sharded_multihost``: every process feeds its
  OWN rows to its OWN devices (``jax.make_array_from_process_local_data``
  — no single-NIC funnel), shape consensus runs as two tiny replicated
  collectives, the hash repartition rides the same all_to_all program,
  and each process writes the bucket files its devices own (ownership
  ``b % D`` is globally disjoint, so files never collide on shared
  storage). String columns union their per-process dictionaries over
  shared storage first (``ops.build.unify_vocabs_shared_storage`` —
  vocabs are ragged bytes, so they ride the same shared storage the
  index lives on, with a collective barrier ordering writes before
  reads). Proven end-to-end by tests/test_multihost.py: two OS processes
  × 4 virtual CPU devices rendezvous at a coordinator and their combined
  output — string column included — equals the single-process sharded
  build row-for-row.
"""

from __future__ import annotations

from typing import Optional


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Bring up the JAX distributed (DCN) control plane so every host's
    devices appear in ``jax.devices()``. Call once per process, before any
    other JAX API. No-ops when already initialized."""
    import jax

    if jax.distributed.is_initialized():
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def process_info() -> dict:
    """This process's place in the job (single-process: 1 process, id 0)."""
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
