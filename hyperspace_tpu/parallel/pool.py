"""Host worker-pool utilities for the pipelined index build.

The streaming build (index/stream_builder.py) is a staged pipeline —
ingest decode → device/host partition+sort → spill compute (D2H + decode)
→ spill write → per-bucket merge. Before this module each stage was at
most ONE thread (a single spill worker behind a depth-1 queue), so at
SF100 the build serialized on one host core (BENCH_SCALE_SF100:
phase_spill_compute_s 270s of a 348s build). These are the shared
primitives every stage now runs on:

* :class:`FirstError` — a cross-stage failure latch: the FIRST exception
  anywhere in the pipeline wins, every stage observes it and drains, and
  the main thread re-raises exactly that exception;
* :class:`WorkerPool` — N daemon workers behind a BOUNDED queue
  (backpressure is the memory bound: in-flight work is queue depth +
  worker count, never "whatever the producer managed to enqueue");
* :func:`ordered_map` — parallel map over an iterator that yields
  results in INPUT order with a bounded in-flight window — the parallel
  ingest stage, where chunk order must be preserved so stable-sort tie
  order (hence the built index bytes) is identical to a serial build;
* :func:`run_parallel` — bounded fan-out over a closed task list (the
  per-bucket finalize merges).

Threading rules the implementations follow (hslint HS002): no blocking
call ever runs under a lock — waits go through ``Condition.wait`` /
``Queue`` timeouts so a failed pipeline can always tear down.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, List, Optional, TypeVar

T = TypeVar("T")
R = TypeVar("R")


class FirstError:
    """First-failure latch shared by every stage of one pipeline.

    ``fail()`` records the first exception only (later ones lose — they
    are almost always teardown echoes of the first); ``failed`` is an
    Event so stages can poll without a lock; ``check()`` re-raises the
    recorded exception on the calling thread — the "first error
    re-raised on the main thread" contract of the build's abort story.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._exc: Optional[BaseException] = None
        self.failed = threading.Event()

    def fail(self, exc: BaseException) -> None:
        with self._lock:
            if self._exc is None:
                self._exc = exc
        self.failed.set()

    @property
    def error(self) -> Optional[BaseException]:
        with self._lock:
            return self._exc

    def check(self) -> None:
        if self.failed.is_set():
            exc = self.error
            if exc is not None:
                raise exc


class BoundedSlots:
    """A bounded in-flight slot counter whose acquire is FAILURE-AWARE:
    the wait polls the shared :class:`FirstError` latch, so after a
    pipeline failure (draining pools never release their slots) a
    producer blocked on a slot re-raises the first error instead of
    parking forever. The device build engine bounds its HBM high-water
    with one of these: dispatched-but-unfetched chunks AND in-flight
    staged-run merges each pin device buffers until their fetch."""

    def __init__(self, n: int, failure: FirstError) -> None:
        self._sem = threading.BoundedSemaphore(max(1, int(n)))
        self.failure = failure

    def acquire(self) -> None:
        while not self._sem.acquire(timeout=0.05):
            self.failure.check()

    def release(self) -> None:
        self._sem.release()


class WorkerPool:
    """N daemon threads draining a bounded task queue.

    Tasks are zero-arg callables. A task that raises latches the shared
    :class:`FirstError`; after a failure (or :meth:`abort`) workers keep
    draining the queue WITHOUT running tasks, so producers blocked on the
    bounded ``submit`` always unblock and ``close`` always joins — no
    parked threads, whatever order the pipeline died in.
    """

    def __init__(
        self,
        workers: int,
        name: str,
        queue_depth: int = 2,
        failure: Optional[FirstError] = None,
    ) -> None:
        self.workers = max(1, int(workers))
        self.failure = failure if failure is not None else FirstError()
        self._discard = threading.Event()
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(queue_depth)))
        self._threads = [
            threading.Thread(target=self._run, daemon=True, name=f"{name}-{i}")
            for i in range(self.workers)
        ]
        self._closed = False
        for t in self._threads:
            t.start()

    def _run(self) -> None:
        while True:
            task = self._q.get()
            if task is None:
                return
            if self._discard.is_set() or self.failure.failed.is_set():
                continue  # drain so producers/close never block forever
            try:
                task()
            except BaseException as e:  # noqa: BLE001 - latched, re-raised on main
                self.failure.fail(e)

    def submit(self, task: Callable[[], None]) -> bool:
        """Bounded enqueue. Returns False (task NOT queued) once the
        pipeline has failed or the pool is draining — the caller should
        then ``failure.check()`` to surface the original error."""
        while not self._discard.is_set() and not self.failure.failed.is_set():
            try:
                self._q.put(task, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def close(self) -> None:
        """Finish queued work (unless failed/aborted — then drain) and
        join every worker. Idempotent."""
        if self._closed:
            return
        self._closed = True
        for _ in self._threads:
            self._q.put(None)  # workers always drain, so this unblocks
        for t in self._threads:
            t.join()

    def abort(self) -> None:
        """Discard queued work and join. Running tasks finish (file
        writes stay atomic); queued ones are dropped."""
        self._discard.set()
        self.close()


def ordered_map(
    fn: Callable[[T], R],
    items: Iterable[T],
    workers: int,
    window: int,
    name: str = "ordered-map",
    failure: Optional[FirstError] = None,
) -> Iterator[R]:
    """Apply ``fn`` to ``items`` on ``workers`` threads, yielding results
    in INPUT order with at most ``window`` items past the consumer.

    The input iterator is advanced under the coordination lock — it must
    be cheap (yield descriptions of work, e.g. zero-arg decode tasks);
    the expensive part belongs in ``fn``. Any failure — in the iterator,
    in ``fn``, or injected through a shared ``failure`` latch — stops
    all workers and re-raises at the consumer. Closing the generator
    mid-stream (consumer abandons) tears the workers down without
    running the remaining items.
    """
    fail = failure if failure is not None else FirstError()
    stop = threading.Event()
    cond = threading.Condition()
    results: dict = {}
    state = {"submitted": 0, "yielded": 0, "exhausted": False}
    it = iter(items)
    workers = max(1, int(workers))
    window = max(workers, int(window))

    def work() -> None:
        while True:
            if stop.is_set() or fail.failed.is_set():
                return
            with cond:
                if state["exhausted"]:
                    return
                if state["submitted"] - state["yielded"] >= window:
                    cond.wait(0.05)
                    continue
                try:
                    item = next(it)
                except StopIteration:
                    state["exhausted"] = True
                    cond.notify_all()
                    return
                except BaseException as e:  # noqa: BLE001 - latched for consumer
                    fail.fail(e)
                    state["exhausted"] = True
                    cond.notify_all()
                    return
                seq = state["submitted"]
                state["submitted"] += 1
            try:
                res = fn(item)
            except BaseException as e:  # noqa: BLE001 - latched for consumer
                fail.fail(e)
                with cond:
                    cond.notify_all()
                return
            with cond:
                results[seq] = res
                cond.notify_all()

    threads = [
        threading.Thread(target=work, daemon=True, name=f"{name}-{i}")
        for i in range(workers)
    ]
    for t in threads:
        t.start()
    try:
        seq = 0
        while True:
            with cond:
                while seq not in results:
                    fail.check()
                    if state["exhausted"] and state["submitted"] == seq:
                        return
                    cond.wait(0.05)
                res = results.pop(seq)
                state["yielded"] += 1
                cond.notify_all()
            yield res
            seq += 1
    finally:
        stop.set()
        with cond:
            cond.notify_all()
        for t in threads:
            t.join()


def run_parallel(
    tasks: List[Callable[[], R]],
    workers: int,
    name: str = "fanout",
) -> List[R]:
    """Run a closed list of tasks across ``workers`` threads; results in
    task order; the first failure cancels the rest and re-raises here."""
    if not tasks:
        return []
    if workers <= 1 or len(tasks) == 1:
        return [t() for t in tasks]
    return list(
        ordered_map(lambda t: t(), tasks, workers, window=len(tasks), name=name)
    )
