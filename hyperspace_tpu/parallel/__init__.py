"""Host- and device-parallelism utilities.

``mesh`` builds the device mesh (bucket parallelism over chips);
``pool`` is the HOST worker-pool layer the pipelined index build runs on
(bounded queues, ordered parallel map, cross-stage failure propagation).
"""

from .pool import FirstError, WorkerPool, ordered_map, run_parallel

__all__ = ["FirstError", "WorkerPool", "ordered_map", "run_parallel"]
