"""Device-mesh construction and sharding helpers.

This is the framework's replacement for Spark's executor pool (SURVEY.md
§2.2): a 1-D ``jax.sharding.Mesh`` whose single axis carries *bucket
parallelism* — bucket b of an index lives on device ``b % n_devices``, so
bucketed operations (per-bucket sort, bucketed sort-merge join,
BucketUnion) are device-local and the only collective is the hash-
repartition all_to_all that rides ICI.
"""

from __future__ import annotations

from typing import Optional

from .. import constants as C
from ..ops import ensure_x64

ensure_x64()

import jax  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec  # noqa: E402

BUCKET_AXIS = C.TPU_MESH_BUCKET_AXIS_DEFAULT


def make_mesh(n_devices: Optional[int] = None, axis: str = BUCKET_AXIS) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` local devices (all by
    default)."""
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"Requested {n_devices} devices but only {len(devices)} present."
            )
        devices = devices[:n_devices]
    import numpy as np

    return Mesh(np.array(devices), (axis,))


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Shard axis 0 (rows) across the bucket axis."""
    return NamedSharding(mesh, PartitionSpec(mesh.axis_names[0]))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def owner_of_bucket(bucket: int, n_devices: int) -> int:
    """THE bucket→device placement rule. Build and query must agree (the
    analog of the reference's BucketSpec-driven task placement) — a
    silent divergence corrupts joins, so the rule exists exactly once:
    this scalar form, ``owner_of_bucket_array`` (the vectorized host
    twin the build's capacity planner and the shuffle planner consume),
    and ``owner_of_bucket_device`` (the traceable twin inside the
    all_to_all kernels). All three are the same modular expression."""
    return bucket % n_devices


def owner_of_bucket_array(buckets, n_devices: int):
    """Vectorized host twin of ``owner_of_bucket`` (numpy array in/out).
    The sharded build's capacity planner and the shuffle planner both
    route through here so their placement can never drift from the
    scalar rule."""
    return buckets % n_devices


def owner_of_bucket_device(buckets, n_devices: int):
    """Device (traceable) twin of ``owner_of_bucket`` for use inside
    jitted shard_map programs — the build and shuffle all_to_all kernels
    compute destination devices with this exact expression."""
    return buckets % n_devices


# -- multi-controller (one process per host) ---------------------------------
# The DCN/ICI scale-out story lives in docs/05-scale-and-distribution.md;
# the multi-controller build itself is ops.build.build_partition_sharded_
# multihost (proven by tests/test_multihost.py). These two helpers are the
# whole control-plane seam.


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Bring up the JAX distributed (DCN) control plane so every host's
    devices appear in ``jax.devices()``. Call once per process, before any
    other JAX API. No-ops when already initialized."""
    if jax.distributed.is_initialized():
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def process_info() -> dict:
    """This process's place in the job (single-process: 1 process, id 0)."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
